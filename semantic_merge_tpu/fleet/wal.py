"""Per-router dispatch WAL — durable in-flight requests.

The router journals every merge request *before* dispatching it to a
member and acknowledges it after the response is written back to the
client. The journal is what turns two crash windows into retries
instead of losses:

- **member crash mid-request** — the dispatching thread observes the
  transport failure and retries on the rehashed owner; the WAL entry
  just stays open a little longer.
- **router crash** — on restart the router replays every journaled
  entry without an ack to the entry's current owner. The client never
  got an answer, so it is retrying anyway; replay makes the *effect*
  happen even if every client gave up.

Exactly-once effects come from the layers below, not the WAL itself:
every journaled request carries the client's idempotency key (the
router mints one when the client didn't), so a member that already
executed the request replays its cached response, and a re-executed
``--inplace`` merge is byte-safe under the PR 4 inplace journal +
repo lockfile. The WAL only has to guarantee *at-least-once* dispatch
with stable keys; the idempotency layer collapses that to
exactly-once effects.

Format: one append-only JSONL file (``wal.jsonl``) inside the router's
WAL directory (default ``<socket>.semmerge-fleet-wal/``). Records:

- ``{"kind": "request", "key", "verb", "params", "trace_id", "t"}``
  — fsync'd before the first dispatch; ``params`` is the full wire
  params dict so replay needs no other source.
- ``{"kind": "dispatch", "key", "member", "t"}`` — one per attempt
  (audit trail for the chaos harness; not fsync'd).
- ``{"kind": "ack", "key", "t"}`` — the response reached (or was
  written toward) the client; the entry is settled.

Torn tails happen (SIGKILL mid-append): the reader skips undecodable
lines, which can only lose the *last* record — a lost ``request`` was
never dispatched (the client saw a transport error and retries), a
lost ``ack`` causes one harmless idempotent replay.

On :meth:`WriteAheadLog.open` the previous incarnation's file is
archived as a numbered segment (``wal.<n>.jsonl``) and the open
entries are carried into a fresh ``wal.jsonl`` — the active file stays
bounded by the in-flight window while the segments preserve the full
dispatch/ack history for the chaos harness's duplicate-commit audit.
Only the most recent :data:`KEEP_SEGMENTS` segments are retained.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: Journal directory name suffix (appended to the router socket path).
WAL_DIRNAME = ".semmerge-fleet-wal"
#: Journal file inside the WAL directory.
WAL_FILE = "wal.jsonl"
#: Documented record kinds (``scripts/check_trace_schema.py
#: validate_fleet`` pins the shapes).
RECORD_KINDS = ("request", "dispatch", "ack")
#: Archived segments kept after an open/compact cycle.
KEEP_SEGMENTS = 16


def default_dir(socket_path: str) -> str:
    """The per-router WAL directory for a router socket path."""
    return socket_path + WAL_DIRNAME


class WriteAheadLog:
    """Append-only, fsync'd-on-request dispatch journal.

    Thread-safe: the router's per-connection threads append
    concurrently under one lock. Every mutator is crash-tolerant in
    the direction that matters — a ``request`` record is on disk
    before the caller may dispatch, everything else is best-effort.
    """

    def __init__(self, directory: str) -> None:
        self.directory = directory
        self.path = os.path.join(directory, WAL_FILE)
        self._lock = threading.Lock()
        self._fh: Optional[Any] = None
        self._open_keys: Dict[str, Dict[str, Any]] = {}
        self.replayable: List[Dict[str, Any]] = []

    # -- lifecycle ---------------------------------------------------

    def open(self) -> List[Dict[str, Any]]:
        """Open (creating the directory), archive + compact, and return
        the entries journaled-but-unacked by a previous incarnation —
        the replay set for this router start."""
        os.makedirs(self.directory, exist_ok=True)
        pending = self._read_pending()
        if os.path.exists(self.path):
            self._archive_current()
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            for rec in pending:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._open_keys = {rec["key"]: rec for rec in pending}
        self.replayable = list(pending)
        return list(pending)

    def _archive_current(self) -> None:
        nums = [0]
        for name in os.listdir(self.directory):
            if name.startswith("wal.") and name.endswith(".jsonl"):
                mid = name[len("wal."):-len(".jsonl")]
                if mid.isdigit():
                    nums.append(int(mid))
        nxt = max(nums) + 1
        os.replace(self.path,
                   os.path.join(self.directory, f"wal.{nxt}.jsonl"))
        stale = sorted(n for n in nums if n)[:-KEEP_SEGMENTS]
        for n in stale:
            try:
                os.unlink(os.path.join(self.directory,
                                       f"wal.{n}.jsonl"))
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    # -- mutators ----------------------------------------------------

    def record_request(self, key: str, verb: str,
                       params: Dict[str, Any],
                       trace_id: Optional[str]) -> None:
        """Journal a request durably (fsync) before first dispatch.

        Re-journaling an already-open key is a no-op: a replayed entry
        keeps its original record.
        """
        with self._lock:
            if key in self._open_keys:
                return
            rec = {"kind": "request", "key": key, "verb": verb,
                   "params": params, "trace_id": trace_id,
                   "t": time.time()}
            self._append(rec, durable=True)
            self._open_keys[key] = rec

    def record_dispatch(self, key: str, member: str) -> None:
        """Audit one dispatch attempt (best-effort, not fsync'd)."""
        with self._lock:
            self._append({"kind": "dispatch", "key": key,
                          "member": member, "t": time.time()},
                         durable=False)

    def ack(self, key: str) -> None:
        """Settle an entry. A lost ack (crash right after the response)
        costs one idempotent replay, never a wrong result."""
        with self._lock:
            if key not in self._open_keys:
                return
            self._append({"kind": "ack", "key": key,
                          "t": time.time()}, durable=False)
            del self._open_keys[key]

    def open_count(self) -> int:
        with self._lock:
            return len(self._open_keys)

    # -- internals ---------------------------------------------------

    def _append(self, rec: Dict[str, Any], *, durable: bool) -> None:
        if self._fh is None:  # closed (teardown race) — drop silently
            return
        self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        self._fh.flush()
        if durable:
            os.fsync(self._fh.fileno())

    def _read_pending(self) -> List[Dict[str, Any]]:
        """Parse the existing journal into its unacked request records
        (in journal order), skipping torn/undecodable lines."""
        requests: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        try:
            fh = open(self.path, "r", encoding="utf-8")
        except OSError:
            return []
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail from a SIGKILL mid-append
                kind = rec.get("kind")
                key = rec.get("key")
                if not isinstance(key, str):
                    continue
                if kind == "request" and key not in requests:
                    requests[key] = rec
                    order.append(key)
                elif kind == "ack":
                    requests.pop(key, None)
        return [requests[k] for k in order if k in requests]


def read_records(directory: str) -> List[Dict[str, Any]]:
    """All decodable records across every retained segment plus the
    active file, oldest first.

    The chaos harness's audit surface: it groups these by key to
    assert every settled request was journaled and that re-journaling
    after replay never happened (exactly-once dispatch accounting).
    """
    paths: List[str] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    nums = []
    for name in names:
        if name.startswith("wal.") and name.endswith(".jsonl"):
            mid = name[len("wal."):-len(".jsonl")]
            if mid.isdigit():
                nums.append(int(mid))
    for n in sorted(nums):
        paths.append(os.path.join(directory, f"wal.{n}.jsonl"))
    paths.append(os.path.join(directory, WAL_FILE))
    out: List[Dict[str, Any]] = []
    for path in paths:
        try:
            fh = open(path, "r", encoding="utf-8")
        except OSError:
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    return out
