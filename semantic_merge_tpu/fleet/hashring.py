"""Rendezvous (highest-random-weight) hashing for repo→member affinity.

Why rendezvous and not a token ring: the fleet is small (single-digit
members) and the property that matters is *minimal disruption* — when
a member fails, only the keys it owned move, and they move to the
member that was already each key's second choice. Rendezvous hashing
gives exactly that with no virtual-node bookkeeping: every (key,
member) pair gets an independent uniform score, a key's owner is the
highest-scoring member, and removing a member can only promote the
runner-up for the keys it owned — every other key's ranking is
untouched. The full descending ranking doubles as the failover order
and the hedge-target order, so routing, failover, and hedging all
share one deterministic notion of "who serves this repo".

Keys are canonicalized repo roots (``repo_key``) so that per-repo
state — the inplace lockfile, decl caches, warm compiled programs —
concentrates on one member across requests and across failovers.

Pure stdlib, no service imports: unit-testable without a daemon.
"""
from __future__ import annotations

import hashlib
import os
from typing import List, Sequence


def repo_key(cwd: str) -> str:
    """Canonical affinity key for a request working directory.

    Resolves symlinks and normalizes so that every spelling of the
    same repo root hashes identically. The *request* cwd (not the git
    toplevel) is deliberate: the router stays git-free and the cwd is
    what the member daemon chdirs to anyway, so affinity follows the
    directory clients actually merge from.
    """
    try:
        return os.path.realpath(cwd or ".")
    except OSError:
        return os.path.normpath(cwd or ".")


def _score(key: str, member: str) -> int:
    digest = hashlib.blake2b(
        key.encode("utf-8", "surrogateescape") + b"\x00" +
        member.encode("utf-8", "surrogateescape"),
        digest_size=8).digest()
    return int.from_bytes(digest, "big")


def rank(key: str, members: Sequence[str]) -> List[str]:
    """Members ranked best-first for ``key`` (deterministic total order).

    ``rank(key, members)[0]`` is the owner; ``[1]`` is the failover /
    hedge target; ties (astronomically unlikely with 64-bit scores)
    break on the member id so the order is total either way.
    """
    return sorted(members, key=lambda m: (_score(key, m), m),
                  reverse=True)


def owner(key: str, members: Sequence[str]) -> str:
    """The single owning member for ``key`` (raises on empty fleet)."""
    if not members:
        raise ValueError("rendezvous rank over an empty member set")
    best = members[0]
    best_score = (_score(key, best), best)
    for m in members[1:]:
        s = (_score(key, m), m)
        if s > best_score:
            best, best_score = m, s
    return best


def moved_keys(keys: Sequence[str], before: Sequence[str],
               after: Sequence[str]) -> List[str]:
    """Keys whose owner changes between two member sets.

    Used by the router to count ``fleet_rehash_moves_total`` when a
    member is ejected, and by tests to assert the minimal-disruption
    property (shrinking the set moves only the dead member's keys).
    """
    if not before or not after:
        return list(keys) if (before or after) else []
    return [k for k in keys if owner(k, before) != owner(k, after)]
