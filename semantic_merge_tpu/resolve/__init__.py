"""Conflict-resolution tier — the rung between "compose found
conflicts" and "exit 1".

Today every composed conflict is a terminal result. This package turns
it into an *attempt*: a pluggable :class:`~semantic_merge_tpu.resolve.
base.Resolver` proposes per-conflict candidate resolutions (the
deterministic search-based baseline ships first — DeepMerge
arXiv:2105.07569 and the search-vs-LLM study arXiv:2605.16646 show the
classes we emit are largely recoverable by exactly this shape of
search), and every accepted resolution must pass the verify gates in
:mod:`semantic_merge_tpu.resolve.engine` — re-compose cleanly, byte
parity of untouched regions, typecheck, format. Any gate failure,
scoring tie, or resolver fault falls back to conflict-as-result,
bitwise identical to the tier being off.

Posture (``--resolve`` / ``SEMMERGE_RESOLVE``, read through the
request overlay so daemon/batch requests carry their client's
posture):

- ``off`` (default) — the tier never runs; artifacts, exit codes and
  trees are byte-identical to pre-tier behavior.
- ``auto`` — resolve when possible; a resolver fault is contained
  (postmortem + conflict-as-result), never an exit-code change.
- ``require`` — the tier must be available; a resolver fault exits
  with :class:`~semantic_merge_tpu.errors.ResolveFault`'s documented
  code (17). A run that resolves nothing still exits 1 — ``require``
  governs the tier's availability, not the outcome.

Strict mode (``--no-degrade`` / ``SEMMERGE_STRICT=1``) forces the tier
off regardless of posture: fail-fast runs must not synthesize output.
"""
from __future__ import annotations

#: Accepted ``SEMMERGE_RESOLVE`` / ``--resolve`` values.
POSTURES = ("off", "auto", "require")


def posture(args=None) -> str:
    """The effective resolution posture: the ``--resolve`` flag wins,
    then ``SEMMERGE_RESOLVE`` via the request overlay; anything absent
    or unrecognized is ``off``. Strict-mode suppression is the CLI's
    call (it owns ``_strict_mode``)."""
    from ..utils import reqenv
    flag = getattr(args, "resolve", None) if args is not None else None
    raw = (flag or reqenv.get("SEMMERGE_RESOLVE", "") or "").strip().lower()
    return raw if raw in POSTURES else "off"
