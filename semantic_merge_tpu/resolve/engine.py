"""The resolution engine: propose → verify → accept, with every
escape hatch wired into the existing robustness machinery.

Flow per merge (called from the CLI when compose yields conflicts and
the posture enables the tier):

1. **Propose** — one ``resolution.propose`` span per conflict; the
   per-category circuit breaker (rung ``resolve:<Category>``, reusing
   :mod:`semantic_merge_tpu.service.resilience`) gates the attempt, and
   the ``resolver:propose`` fault-injection stage fires inside the
   span. A unique top-scoring candidate wins; a tie or an empty
   proposal list marks the conflict unresolvable.
2. **Verify** — all-or-nothing: resolution is only attempted when
   *every* conflict has a winner, because the merged tree either
   replaces the conflict exit entirely or not at all (a half-resolved
   tree would be a third output shape nothing downstream expects).
   The gates run in documented order — ``recompose`` (the rewritten
   streams re-compose with zero residual conflicts), ``parity`` (the
   resolved tree is byte-identical to the conflict-free portion of the
   merge everywhere outside the resolution's footprint), ``typecheck``
   (``runtime/verify.py``; vacuous without the toolchain, exactly like
   the main pipeline), ``format`` (the footprint formats cleanly).
   Any gate failure rejects the whole proposal set.
3. **Accept / fall back** — acceptance hands the re-composed stream
   back to the CLI, which materializes it through the normal pipeline;
   every other outcome falls back to conflict-as-result. All outcomes
   land in ``resolutions_total{category,outcome}`` and the artifact's
   ``resolutions`` audit block.

A resolver *fault* (injected or real) escapes as
:class:`~semantic_merge_tpu.errors.ResolveFault` after recording the
breaker failure; the CLI contains it under posture ``auto``
(postmortem + conflict-as-result) and exits 17 under ``require``.
"""
from __future__ import annotations

import shutil
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.ops import Op
from ..errors import MergeFault, fault_boundary
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import spans as obs_spans
from ..service.resilience import breakers
from ..utils import faults
from .base import Candidate, ResolveContext, Resolver
from .search import SearchResolver

#: Verify gates, in the order they run (documented in runbook.md).
GATES = ("recompose", "parity", "typecheck", "format")

#: Documented ``outcome`` label values of ``resolutions_total``.
OUTCOMES = ("accepted", "rejected", "no-candidates", "tie",
            "breaker-open", "fault")

_METRIC_HELP = "Conflict-resolution proposals, by category and outcome"


@dataclass
class ResolutionOutcome:
    """What the tier produced: the audit records always; a re-composed
    op stream only when every gate passed."""

    accepted: bool
    composed: Optional[List[Op]]
    records: List[dict] = field(default_factory=list)


def _count(category: str, outcome: str) -> None:
    obs_metrics.REGISTRY.counter("resolutions_total", _METRIC_HELP).inc(
        1, category=category, outcome=outcome)


def record_resolver_fault(fault: MergeFault) -> None:
    """Containment bookkeeping for a resolver fault the CLI absorbed
    (posture ``auto``): the metric plus a flight-recorder postmortem —
    the tier degraded, and that must leave evidence."""
    from ..utils import workdir
    _count("all", "fault")
    obs_flight.dump(
        obs_spans.trace_id() or obs_flight.default_trace_id(),
        "resolver-fault", fault=fault, breakers=breakers().snapshot(),
        root=workdir.root())


def resolve_conflicts(conflicts: Sequence, log_a: List[Op], log_b: List[Op],
                      *, composed, base_tar: bytes, left_tar: bytes,
                      right_tar: bytes, strict_detect: bool, config,
                      resolver: Optional[Resolver] = None,
                      ) -> ResolutionOutcome:
    """Attempt to resolve ``conflicts`` by rewriting the raw op streams
    and re-running the pipeline's own compose/apply machinery. Never
    mutates its inputs; the only success path returns a freshly
    composed stream that passed every gate."""
    resolver = resolver or SearchResolver()
    ctx = ResolveContext(log_a, log_b, base_tar=base_tar,
                         left_tar=left_tar, right_tar=right_tar)
    board = breakers()

    records: List[dict] = []
    winners: List[Tuple[dict, Candidate]] = []
    for conflict in conflicts:
        cd = conflict.to_dict() if hasattr(conflict, "to_dict") else dict(conflict)
        category = str(cd.get("category", "unknown"))
        rung = f"resolve:{category}"
        rec = {
            "conflict_id": cd.get("id"),
            "category": category,
            "resolver": resolver.name,
            "status": "rejected",
            "cause": None,
            "candidate": None,
            "candidates": 0,
            "scores": {},
            "gates": [],
        }
        records.append(rec)
        if not board.allow(rung):
            rec["cause"] = "breaker-open"
            _count(category, "breaker-open")
            continue
        try:
            with obs_spans.span("resolution.propose", layer="resolve",
                                category=category), \
                    fault_boundary("resolver:propose"):
                faults.check("resolver:propose")
                cands = list(resolver.propose(cd, ctx))
        except MergeFault:
            board.record_failure(rung)
            raise
        rec["candidates"] = len(cands)
        rec["scores"] = {c.id: c.score for c in cands}
        if not cands:
            rec["cause"] = "no-candidates"
            _count(category, "no-candidates")
            continue
        best = max(c.score for c in cands)
        top = [c for c in cands if c.score == best]
        if len(top) > 1 or best <= 0:
            # Equal evidence (or none): choosing would be a guess, and
            # guessing is the one thing this tier must never do.
            rec["cause"] = "tie"
            _count(category, "tie")
            continue
        rec["candidate"] = top[0].audit()
        winners.append((rec, top[0]))

    if len(winners) < len(records):
        # All-or-nothing: a partially resolved merge is still a
        # conflicted merge, so conflicts that DID find a winner are
        # rejected alongside their unresolved peers.
        for rec, _ in winners:
            rec["cause"] = "peer-unresolved"
            _count(rec["category"], "rejected")
        return ResolutionOutcome(False, None, records)

    gates: List[dict] = []
    for rec in records:
        rec["gates"] = gates  # one shared verify run covers the set
    try:
        with obs_spans.span("resolution.verify", layer="resolve",
                            n=len(winners)), \
                fault_boundary("resolver:verify"):
            faults.check("resolver:verify")
            composed2, failed = _verify(
                [c for _, c in winners], log_a, log_b, gates,
                composed=composed, base_tar=base_tar,
                strict_detect=strict_detect, config=config)
    except MergeFault:
        for rec, _ in winners:
            board.record_failure(f"resolve:{rec['category']}")
        raise
    if failed is not None:
        for rec, _ in winners:
            rec["cause"] = f"gate:{failed}"
            _count(rec["category"], "rejected")
            board.record_failure(f"resolve:{rec['category']}")
        return ResolutionOutcome(False, None, records)

    for rec, _ in winners:
        rec["status"] = "accepted"
        _count(rec["category"], "accepted")
        board.record_success(f"resolve:{rec['category']}")
    obs_spans.record("resolution.accept", 0.0, layer="resolve",
                     n=len(winners))
    return ResolutionOutcome(True, composed2, records)


def _gate_row(name: str, ok: bool, t0: float,
              detail: Optional[str] = None) -> dict:
    row = {"gate": name, "ok": ok,
           "ms": round((time.perf_counter() - t0) * 1000.0, 3)}
    if detail:
        row["detail"] = detail
    return row


def _verify(cands: List[Candidate], log_a: List[Op], log_b: List[Op],
            gates: List[dict], *, composed, base_tar: bytes,
            strict_detect: bool, config,
            ) -> Tuple[Optional[List[Op]], Optional[str]]:
    """Run the gate ladder over the united candidate set. Returns
    ``(composed_stream, None)`` on full success or ``(None,
    failed_gate_name)``; each gate appends its audit row either way.
    Gate *failures* are legitimate rejections handled here; only
    unexpected exceptions escape to the caller's fault boundary."""
    from ..core.strict_conflicts import detect_conflicts_strict
    from ..ops.compose import recompose_resolved
    from ..runtime.applier import apply_ops, touched_paths
    from ..runtime.emitter import emit_files
    from ..runtime.git import temp_tree
    from ..runtime.verify import typecheck_ts, untouched_parity

    # -- gate: recompose ----------------------------------------------------
    t0 = time.perf_counter()
    drops: set = set()
    replaces: Dict[str, Op] = {}
    for cand in cands:
        drops.update(cand.drops)
        for op_id, op in cand.replaces.items():
            if op_id in replaces and replaces[op_id].to_dict() != op.to_dict():
                gates.append(_gate_row("recompose", False, t0,
                                       "candidate-overlap"))
                return None, "recompose"
            replaces[op_id] = op
    if drops & set(replaces):
        gates.append(_gate_row("recompose", False, t0, "candidate-overlap"))
        return None, "recompose"
    ta = [replaces.get(op.id, op) for op in log_a if op.id not in drops]
    tb = [replaces.get(op.id, op) for op in log_b if op.id not in drops]
    if strict_detect:
        ka, kb, residual = detect_conflicts_strict(ta, tb)
        composed2, walk = recompose_resolved(ka, kb)
        residual = list(residual) + list(walk)
    else:
        composed2, residual = recompose_resolved(ta, tb)
    if residual:
        gates.append(_gate_row(
            "recompose", False, t0,
            f"{len(residual)} residual conflict(s) after rewrite"))
        return None, "recompose"
    gates.append(_gate_row("recompose", True, t0))

    # -- gate: parity -------------------------------------------------------
    # The resolution's footprint is every file an op that *changed*
    # between the conflict-free stream and the resolved stream can
    # write (chain propagation may rewrite params of a surviving op,
    # so compare materialized records, not just ids). Outside that
    # footprint the two applied trees must match byte for byte.
    t0 = time.perf_counter()
    orig = {op.id: op.to_dict() for op in composed}
    new = {op.id: op.to_dict() for op in composed2}
    changed = [oid for oid in set(orig) | set(new)
               if orig.get(oid) != new.get(oid)]
    footprint: set = set()
    for oid in changed:
        for stream, table in ((composed, orig), (composed2, new)):
            if oid in table:
                src = next(op for op in stream if op.id == oid)
                footprint |= touched_paths([src])
    tree_orig = tree_new = None
    try:
        with temp_tree(base_tar) as base_tree:
            tree_orig = apply_ops(base_tree, list(composed))
        with temp_tree(base_tar) as base_tree:
            tree_new = apply_ops(base_tree, composed2)
        mismatches = untouched_parity(tree_orig, tree_new,
                                      exclude=footprint)
        if mismatches:
            gates.append(_gate_row(
                "parity", False, t0,
                "outside-footprint drift: " + ", ".join(mismatches[:5])))
            return None, "parity"
        gates.append(_gate_row("parity", True, t0))

        # -- gate: typecheck ------------------------------------------------
        t0 = time.perf_counter()
        if getattr(getattr(config, "ci", None), "require_typecheck", False):
            ok, diagnostics = typecheck_ts(tree_new)
            if not ok:
                gates.append(_gate_row(
                    "typecheck", False, t0,
                    "; ".join(diagnostics[:3]) or "type errors"))
                return None, "typecheck"
        gates.append(_gate_row("typecheck", True, t0))

        # -- gate: format ---------------------------------------------------
        t0 = time.perf_counter()
        formatter = None
        languages = getattr(config, "languages", None) or {}
        ts_cfg = languages.get("typescript") if hasattr(languages, "get") \
            else None
        if ts_cfg is not None and getattr(ts_cfg, "formatter_cmd", None):
            formatter = list(ts_cfg.formatter_cmd)
        try:
            emit_files(tree_new, formatter, paths=sorted(footprint))
        except Exception as exc:  # formatter blew past its own guards
            gates.append(_gate_row("format", False, t0,
                                   f"{type(exc).__name__}: {exc}"))
            return None, "format"
        gates.append(_gate_row("format", True, t0))
    finally:
        for tree in (tree_orig, tree_new):
            if tree is not None:
                shutil.rmtree(tree, ignore_errors=True)

    return composed2, None
