"""Resolver contracts: candidates, the per-merge context, and the
``Resolver`` ABC the search baseline (and a later model-backed
resolver) implement.

A candidate is expressed purely as an *op-stream rewrite* — drop these
op ids, replace those ops — never as direct text output. The engine
re-composes and re-materializes the rewritten streams through the
exact same pipeline a conflict-free merge takes, which is what makes
the verify gates meaningful: a resolution is "the merge the branches
would have produced had they not disagreed", not a synthesized patch.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core.ops import Op
from ..runtime.textmerge import tar_file_map


@dataclass(frozen=True)
class Candidate:
    """One proposed resolution of one conflict.

    ``drops`` are op ids removed from whichever side's stream holds
    them; ``replaces`` maps an op id to the op that takes its place
    in-stream. ``score`` is the resolver's evidence weight — the engine
    picks the unique maximum and treats ties as unresolvable (a tie
    means the resolver has no grounds to prefer either side, and
    guessing is exactly what this tier must never do)."""

    id: str
    label: str
    rationale: str
    drops: Tuple[str, ...] = ()
    replaces: Dict[str, Op] = field(default_factory=dict)
    score: int = 0

    def audit(self) -> dict:
        """The artifact-facing shape (op ids only — full replacement
        ops live in the op log, not the conflicts artifact)."""
        return {
            "id": self.id,
            "label": self.label,
            "rationale": self.rationale,
            "drop": sorted(self.drops),
            "replace": sorted(self.replaces),
        }


class ResolveContext:
    """What a resolver may look at: the two raw op streams and the
    three tree snapshots, with lazy, cached path→bytes maps. Everything
    here is read-only evidence — mutation happens only through the
    candidate's drops/replaces, verified by the engine."""

    def __init__(self, log_a: List[Op], log_b: List[Op], *,
                 base_tar: bytes, left_tar: bytes, right_tar: bytes) -> None:
        self.log_a = list(log_a)
        self.log_b = list(log_b)
        self._tars = {"base": base_tar, "left": left_tar, "right": right_tar}
        self._maps: Dict[str, Dict[str, bytes]] = {}
        self._index: Dict[str, Tuple[str, Op]] = {}
        for op in self.log_a:
            self._index.setdefault(op.id, ("A", op))
        for op in self.log_b:
            self._index.setdefault(op.id, ("B", op))

    def tree_map(self, which: str) -> Dict[str, bytes]:
        """Path → bytes of the ``base``/``left``/``right`` snapshot."""
        cached = self._maps.get(which)
        if cached is None:
            cached = self._maps[which] = tar_file_map(self._tars[which])
        return cached

    def side_map(self, side: str) -> Dict[str, bytes]:
        """The snapshot of branch ``"A"`` (left) or ``"B"`` (right)."""
        return self.tree_map("left" if side == "A" else "right")

    def op(self, op_id: str) -> Optional[Op]:
        hit = self._index.get(op_id)
        return hit[1] if hit else None

    def side_of(self, op_id: str) -> Optional[str]:
        hit = self._index.get(op_id)
        return hit[0] if hit else None

    def side_log(self, side: str) -> List[Op]:
        return self.log_a if side == "A" else self.log_b


class Resolver(abc.ABC):
    """A conflict-resolution strategy. Implementations must be pure
    functions of (conflict record, context): no filesystem writes, no
    randomness — determinism is part of the never-worse contract, and
    the model-backed resolver that slots in here later must honor the
    same shape (propose candidates; the engine verifies)."""

    name = "resolver"

    @abc.abstractmethod
    def propose(self, conflict: dict, ctx: ResolveContext) -> List[Candidate]:
        """Candidates for one conflict record (``Conflict.to_dict()``
        shape). An empty list means "no grounds to resolve" — the
        engine records ``cause="no-candidates"`` and falls back."""
