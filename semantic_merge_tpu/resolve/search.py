"""Deterministic search-based baseline resolver.

Per conflict category, enumerate candidate op-stream rewrites from the
conflict's ``opA``/``opB``/``minimalSlice`` and score them on evidence
found in the three tree snapshots — reference counts, cleaned-up call
sites, disjoint statement edits. The strategies are the classic ones
the search-vs-LLM study (arXiv 2605.16646) measures:

- **DivergentRename** — prefer the rename whose side carries a
  reference rewrite: the winning new name is the one actually *used*
  beyond its declaration. Symmetric bare renames score equal → tie →
  fallback.
- **DeleteVsEdit** — apply-edit-then-delete ordering: keep the delete
  when the deleting side also removed the symbol's references (the
  delete was a completed cleanup); keep the edit when the editing side
  added new usages (the symbol became *more* load-bearing).
- **ConcurrentStmtEdit** — line-level 3-way on the statement slice
  (``oldBody`` vs the two ``newBody``\\ s). Disjoint edits merge into
  one body; overlapping edits yield no candidate.
- **ExtractVsInline** — keep the motion whose side shows the stronger
  reference evidence (extracted helper actually called / inlined
  callee's call sites actually gone). The losing motion's companion
  ops (the body edit and the add/delete of the moved declaration) drop
  with it, mirroring ``core.strict_conflicts``'s consumption rule.

Scores are small integers derived from whole-word reference counts —
deterministic, explainable, and recorded per candidate in the audit
trail. Categories without a strategy (``DivergentMove``,
``IncompatibleSignatureChange``) propose nothing and fall back.
"""
from __future__ import annotations

import difflib
import re
from typing import Dict, List, Optional, Tuple

from ..core.ids import stable_hash_hex
from .base import Candidate, ResolveContext, Resolver


def _refs(name: str, file_map: Dict[str, bytes]) -> int:
    """Whole-word occurrences of ``name`` across a snapshot's decodable
    files. Identifier boundaries are the TS identifier alphabet, so
    ``foo`` does not count inside ``fooBar`` or ``my_foo``."""
    if not name:
        return 0
    pat = re.compile(r"(?<![A-Za-z0-9_$])" + re.escape(name)
                     + r"(?![A-Za-z0-9_$])")
    total = 0
    for data in file_map.values():
        try:
            text = data.decode("utf-8")
        except UnicodeDecodeError:
            continue
        total += len(pat.findall(text))
    return total


def _addr_symbol(address_id: Optional[str]) -> str:
    """The symbol *name* embedded in a ``path::name::n`` address id."""
    parts = (address_id or "").split("::")
    return parts[1] if len(parts) >= 2 else ""


class SearchResolver(Resolver):
    """The deterministic baseline. ``propose`` dispatches on the
    conflict's ``category``; every branch is a pure function of the
    record and the snapshots."""

    name = "search"

    def propose(self, conflict: dict, ctx: ResolveContext) -> List[Candidate]:
        handler = {
            "DivergentRename": self._divergent_rename,
            "DeleteVsEdit": self._delete_vs_edit,
            "ConcurrentStmtEdit": self._concurrent_stmt_edit,
            "ExtractVsInline": self._extract_vs_inline,
        }.get(str(conflict.get("category", "")))
        if handler is None:
            return []
        return handler(conflict, ctx)

    # -- DivergentRename ----------------------------------------------------

    def _divergent_rename(self, conflict: dict,
                          ctx: ResolveContext) -> List[Candidate]:
        op_a, op_b = conflict.get("opA", {}), conflict.get("opB", {})
        name_a = str(op_a.get("params", {}).get("newName") or "")
        name_b = str(op_b.get("params", {}).get("newName") or "")
        out = []
        for keep, drop, name, side in (("keepA", op_b, name_a, "A"),
                                       ("keepB", op_a, name_b, "B")):
            drop_id = str(drop.get("id") or "")
            if not drop_id or not name:
                continue
            out.append(Candidate(
                id=keep, label=f"Rename to {name}",
                rationale=f"{_refs(name, ctx.side_map(side))} whole-word "
                          f"references to {name!r} on side {side} — the "
                          "rewritten references are the winning rename's "
                          "evidence",
                drops=(drop_id,),
                score=_refs(name, ctx.side_map(side))))
        return out

    # -- DeleteVsEdit -------------------------------------------------------

    def _delete_vs_edit(self, conflict: dict,
                        ctx: ResolveContext) -> List[Candidate]:
        op_a, op_b = conflict.get("opA", {}), conflict.get("opB", {})
        if op_a.get("type") == "deleteDecl":
            op_del, op_edit = op_a, op_b
        elif op_b.get("type") == "deleteDecl":
            op_del, op_edit = op_b, op_a
        else:
            return []
        del_id = str(op_del.get("id") or "")
        edit_id = str(op_edit.get("id") or "")
        if not del_id or not edit_id:
            return []
        name = _addr_symbol(op_del.get("target", {}).get("addressId"))
        del_side = ctx.side_of(del_id)
        edit_side = ctx.side_of(edit_id)
        if del_side is None or edit_side is None:
            return []
        base_refs = _refs(name, ctx.tree_map("base"))
        del_refs = _refs(name, ctx.side_map(del_side))
        edit_refs = _refs(name, ctx.side_map(edit_side))
        # Cleanup evidence: references removed beyond the declaration
        # itself (the -1). Usage evidence: references the edit added.
        keep_delete = max(0, base_refs - del_refs - 1)
        keep_edit = max(0, edit_refs - base_refs)
        return [
            Candidate(
                id="keepDelete", label="Keep the deletion",
                rationale=f"deleting side removed {keep_delete} "
                          f"reference(s) to {name!r} beyond the "
                          "declaration — apply-edit-then-delete ordering",
                drops=(edit_id,), score=keep_delete),
            Candidate(
                id="keepEdit", label="Keep the edit",
                rationale=f"editing side added {keep_edit} new "
                          f"reference(s) to {name!r} — the symbol grew "
                          "more load-bearing",
                drops=(del_id,), score=keep_edit),
        ]

    # -- ConcurrentStmtEdit -------------------------------------------------

    def _concurrent_stmt_edit(self, conflict: dict,
                              ctx: ResolveContext) -> List[Candidate]:
        op_a, op_b = conflict.get("opA", {}), conflict.get("opB", {})
        id_a, id_b = str(op_a.get("id") or ""), str(op_b.get("id") or "")
        live_a = ctx.op(id_a)
        if live_a is None or not id_b:
            return []
        old = str(op_a.get("params", {}).get("oldBody") or "")
        new_a = str(op_a.get("params", {}).get("newBody") or "")
        new_b = str(op_b.get("params", {}).get("newBody") or "")
        merged = _merge3_lines(old, new_a, new_b)
        if merged is None:
            return []
        rep = live_a.clone()
        rep.params["newBody"] = merged
        rep.params["newBodyHash"] = stable_hash_hex(merged, n_hex=16)
        return [Candidate(
            id="merged3way", label="Merge both body edits",
            rationale="the two body edits touch disjoint statement "
                      "lines — token-level 3-way on the minimal slice "
                      "composes them",
            drops=(id_b,), replaces={id_a: rep}, score=1)]

    # -- ExtractVsInline ----------------------------------------------------

    def _extract_vs_inline(self, conflict: dict,
                           ctx: ResolveContext) -> List[Candidate]:
        op_a, op_b = conflict.get("opA", {}), conflict.get("opB", {})
        if op_a.get("type") == "extractMethod":
            op_ext, op_inl = op_a, op_b
        elif op_b.get("type") == "extractMethod":
            op_ext, op_inl = op_b, op_a
        else:
            return []
        ext_id, inl_id = str(op_ext.get("id") or ""), str(op_inl.get("id") or "")
        ext_side, inl_side = ctx.side_of(ext_id), ctx.side_of(inl_id)
        if ext_side is None or inl_side is None:
            return []
        new_name = str(op_ext.get("params", {}).get("newName") or "")
        method = str(op_inl.get("params", {}).get("methodName") or "")
        # Keeping one motion drops the other motion AND its companion
        # text-level ops — the body edit on the host decl and the
        # add/delete of the moved declaration — exactly the set
        # ``strict_conflicts`` consumes when it reports the conflict.
        ext_drops = _companion_ids(ctx, ext_id, ext_side)
        inl_drops = _companion_ids(ctx, inl_id, inl_side)
        base_refs = _refs(method, ctx.tree_map("base"))
        inl_refs = _refs(method, ctx.side_map(inl_side))
        return [
            Candidate(
                id="keepExtract", label=f"Keep the extracted {new_name}",
                rationale=f"{_refs(new_name, ctx.side_map(ext_side))} "
                          f"reference(s) to the extracted {new_name!r} "
                          "on the extracting side",
                drops=inl_drops,
                score=_refs(new_name, ctx.side_map(ext_side))),
            Candidate(
                id="keepInline", label=f"Keep {method} inlined",
                rationale=f"inlining side removed "
                          f"{max(0, base_refs - inl_refs - 1)} call "
                          f"site(s) of {method!r}",
                drops=ext_drops,
                score=max(0, base_refs - inl_refs - 1)),
        ]


def _companion_ids(ctx: ResolveContext, motion_id: str,
                   side: str) -> Tuple[str, ...]:
    """The motion op's id plus its companions' ids in its own stream —
    the mirror of ``core.strict_conflicts``'s ``companions`` rule."""
    motion = ctx.op(motion_id)
    if motion is None:
        return (motion_id,)
    if motion.type == "extractMethod":
        addr, decl_t = motion.params.get("newAddress"), "addDecl"
    else:
        addr, decl_t = motion.params.get("oldAddress"), "deleteDecl"
    out = [motion_id]
    for op in ctx.side_log(side):
        if (op.type == "editStmtBlock"
                and op.target.symbolId == motion.target.symbolId
                and op.target.addressId == motion.target.addressId):
            out.append(op.id)
        elif op.type == decl_t and op.target.addressId == addr:
            out.append(op.id)
    return tuple(out)


def _merge3_lines(base: str, a: str, b: str) -> Optional[str]:
    """Line-level 3-way merge of one statement body; ``None`` when the
    two sides' edits overlap (including both inserting different text
    at the same point — ordering would be a guess)."""
    base_lines = base.splitlines(keepends=True)
    edits: List[Tuple[int, int, List[str], str]] = []
    for side, text in (("A", a), ("B", b)):
        lines = text.splitlines(keepends=True)
        sm = difflib.SequenceMatcher(a=base_lines, b=lines, autojunk=False)
        for tag, lo, hi, blo, bhi in sm.get_opcodes():
            if tag != "equal":
                edits.append((lo, hi, lines[blo:bhi], side))
    for i, (lo_a, hi_a, rep_a, s_a) in enumerate(edits):
        for lo_b, hi_b, rep_b, s_b in edits[i + 1:]:
            if s_a == s_b:
                continue
            if (lo_a, hi_a, rep_a) == (lo_b, hi_b, rep_b):
                continue  # both sides made the identical edit
            if max(lo_a, lo_b) < min(hi_a, hi_b):
                return None
            if lo_a == hi_a == lo_b == hi_b and rep_a != rep_b:
                return None
    # Deduplicate identical edits (both sides made the same change),
    # then splice sorted-by-position into the base.
    uniq: List[Tuple[int, int, Tuple[str, ...]]] = []
    for lo, hi, rep, _ in edits:
        key = (lo, hi, tuple(rep))
        if key not in uniq:
            uniq.append(key)
    uniq.sort()
    out: List[str] = []
    cursor = 0
    for lo, hi, rep in uniq:
        out.extend(base_lines[cursor:lo])
        out.extend(rep)
        cursor = hi
    out.extend(base_lines[cursor:])
    return "".join(out)
