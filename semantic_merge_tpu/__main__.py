"""Package entry point.

Daemon delegation happens HERE, before ``cli`` (and with it jax, the
backends, the engine) is imported: when ``SEMMERGE_DAEMON=auto|require``
hands a merge-shaped invocation to a warm daemon, this process only
ever pays for the thin client (:mod:`semantic_merge_tpu.service.client`)
— milliseconds instead of the cold-start imports the daemon exists to
amortize. Any path that does not delegate (mode off, non-verb command,
auto-mode fallback) proceeds through the normal CLI unchanged.
"""
import sys


def _main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    from .service import client
    code = client.delegate(argv)
    if code is not None:
        return code
    from .cli import main
    return main(argv)


if __name__ == "__main__":
    sys.exit(_main())
