from .ops import Op, OpLog, OpType, Target, OP_TYPES, OP_PRECEDENCE
from .conflict import Conflict, divergent_rename_conflict

__all__ = [
    "Op",
    "OpLog",
    "OpType",
    "Target",
    "OP_TYPES",
    "OP_PRECEDENCE",
    "Conflict",
    "divergent_rename_conflict",
]
