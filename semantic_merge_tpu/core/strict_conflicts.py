"""Strict conflict detection — the [CFR-002] categories.

The reference *requires* six conflict categories (reference
``requirements.md:93-99`` [CFR-002]) but implements exactly one,
DivergentRename, and only when the two renames surface simultaneously
at both compose cursors (reference ``semmerge/compose.py:60-70`` —
interleaved ops can mask it). This module implements the categories
expressible over the implemented op vocabulary as a full symbol-level
join, immune to interleaving:

- **DivergentRename** — both sides rename one symbol to different names.
- **DivergentMove** — both sides move one symbol to different addresses.
- **IncompatibleSignatureChange** — both sides change one symbol's
  signature differently (requires ``changeSignature`` extraction).
- **DeleteVsEdit** — one side deletes a declaration the other side
  renames / moves / re-signs / body-edits.
- **ConcurrentStmtEdit** — both sides edited one declaration's body
  to different results (requires ``editStmtBlock`` extraction —
  ``core.difflift.statement_edits``, enabled automatically in strict
  mode).
- **ExtractVsInline** — one side extracted a statement block into a
  new declaration while the other inlined a declaration with that same
  block (requires ``extractMethod``/``inlineMethod`` extraction —
  ``core.difflift.body_motions``, enabled automatically in strict
  mode). Joined on ``blockHash``, the content identity of the moved
  statements. All six [CFR-002] categories are now implemented; the
  reference names this one (reference ``requirements.md:98``) but its
  worker has no extractor. The same pass applies [RES-004]: both sides
  extracting the same block with identical bodies deduplicate to one
  declaration (A's kept) instead of conflicting.

Semantics: conflicting ops drop from both streams (the reference's
DivergentRename drop semantics, generalized), the pre-pass runs before
composition, and the composer then finds no residual head-vs-head
conflicts. Selected via ``[engine] conflict_mode = "strict"`` or
``--strict-conflicts``; the default ``"parity"`` keeps the reference's
observable behavior bit-for-bit.

This is the host oracle of the sharded-join design: the device twin is
the same sorted self-join the TPU composer already runs for its
DivergentRename prescreen (:mod:`semantic_merge_tpu.ops.compose`),
extended with the per-category predicates — all segmented comparisons
on (symbolId-sorted) op tensors.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .conflict import (Conflict, concurrent_stmt_edit_conflict,
                       delete_vs_edit_conflict, divergent_rename_conflict,
                       extract_vs_inline_conflict)
from .ops import Op

_EDIT_TYPES = ("renameSymbol", "moveDecl", "changeSignature",
               "editStmtBlock")


def detect_conflicts_strict(delta_a: List[Op], delta_b: List[Op],
                            ) -> Tuple[List[Op], List[Op], List[Conflict]]:
    """Full-stream conflict join; returns the two streams with
    conflicting ops dropped plus the conflict records (stable order:
    by first involved A-op's stream position; detection-order ties keep
    emission order). Every conflict records which A-stream op it
    involves at emission time, and the final list sorts on that
    position — so the documented ordering holds even though the
    motion pass runs before the per-symbol loops."""
    by_sym_a = _group(delta_a)
    by_sym_b = _group(delta_b)

    drop_a: set = set()
    drop_b: set = set()
    # (A-op stream position, conflict) pairs; sorted (stably) at the
    # end so the motion-pass-first detection schedule does not leak
    # into the output order.
    pos_a = {id(op): i for i, op in enumerate(delta_a)}
    keyed: List[Tuple[int, Conflict]] = []

    def emit(a_op: Op, conflict: Conflict) -> None:
        keyed.append((pos_a.get(id(a_op), len(delta_a)), conflict))

    # Body-motion pass first (cross-symbol join on blockHash): an
    # ExtractVsInline conflict consumes the motion's companion
    # editStmtBlock/addDecl/deleteDecl ops too, so the per-symbol
    # loops below must not re-report the same disagreement as
    # ConcurrentStmtEdit or DeleteVsEdit. Consumption is tracked apart
    # from the plain drop sets: ops dropped *within* a later loop keep
    # their established pairing behavior.
    consumed_a: set = set()
    consumed_b: set = set()
    _motion_pass(delta_a, delta_b, consumed_a, consumed_b, emit)
    drop_a |= consumed_a
    drop_b |= consumed_b

    for sym, ops_a in by_sym_a.items():
        ops_b = by_sym_b.get(sym)
        if not ops_b:
            continue

        ren_a = [op for op in ops_a if op.type == "renameSymbol"]
        ren_b = [op for op in ops_b if op.type == "renameSymbol"]
        for op_a in ren_a:
            for op_b in ren_b:
                if op_a.params.get("newName") != op_b.params.get("newName"):
                    emit(op_a, divergent_rename_conflict(op_a, op_b))
                    drop_a.add(id(op_a))
                    drop_b.add(id(op_b))

        mov_a = [op for op in ops_a if op.type == "moveDecl"]
        mov_b = [op for op in ops_b if op.type == "moveDecl"]
        for op_a in mov_a:
            for op_b in mov_b:
                if op_a.params.get("newAddress") != op_b.params.get("newAddress"):
                    emit(op_a, divergent_move_conflict(op_a, op_b))
                    drop_a.add(id(op_a))
                    drop_b.add(id(op_b))

        sig_a = [op for op in ops_a if op.type == "changeSignature"]
        sig_b = [op for op in ops_b if op.type == "changeSignature"]
        for op_a in sig_a:
            for op_b in sig_b:
                if op_a.params.get("newSignature") != op_b.params.get("newSignature"):
                    emit(op_a, incompatible_signature_conflict(op_a, op_b))
                    drop_a.add(id(op_a))
                    drop_b.add(id(op_b))

        stm_a = [op for op in ops_a if op.type == "editStmtBlock"]
        stm_b = [op for op in ops_b if op.type == "editStmtBlock"]
        for op_a in stm_a:
            for op_b in stm_b:
                # Skip only when the motion pass consumed BOTH sides —
                # that pair IS the disagreement the motion conflict
                # reported. One-sided consumption means the other
                # side's differing edit is its own disagreement.
                if id(op_a) in consumed_a and id(op_b) in consumed_b:
                    continue
                # Same decl (same address), bodies edited to different
                # results; identical edits agree and pass through.
                if (op_a.target.addressId == op_b.target.addressId
                        and op_a.params.get("newBodyHash")
                        != op_b.params.get("newBodyHash")):
                    emit(op_a, concurrent_stmt_edit_conflict(op_a, op_b))
                    drop_a.add(id(op_a))
                    drop_b.add(id(op_b))

        del_a = [op for op in ops_a if op.type == "deleteDecl"]
        del_b = [op for op in ops_b if op.type == "deleteDecl"]
        edit_a = [op for op in ops_a if op.type in _EDIT_TYPES]
        edit_b = [op for op in ops_b if op.type in _EDIT_TYPES]
        for op_del in del_a:
            for op_edit in edit_b:
                if id(op_del) in consumed_a and id(op_edit) in consumed_b:
                    continue
                emit(op_del, delete_vs_edit_conflict(op_del, op_edit, "A"))
                drop_a.add(id(op_del))
                drop_b.add(id(op_edit))
        for op_del in del_b:
            for op_edit in edit_a:
                if id(op_del) in consumed_b and id(op_edit) in consumed_a:
                    continue
                emit(op_edit, delete_vs_edit_conflict(op_del, op_edit, "B"))
                drop_b.add(id(op_del))
                drop_a.add(id(op_edit))

    kept_a = [op for op in delta_a if id(op) not in drop_a]
    kept_b = [op for op in delta_b if id(op) not in drop_b]
    keyed.sort(key=lambda t: t[0])  # stable: ties keep emission order
    return kept_a, kept_b, [c for _, c in keyed]


def _motion_pass(delta_a: List[Op], delta_b: List[Op],
                 consumed_a: set, consumed_b: set,
                 emit) -> None:
    """ExtractVsInline detection plus the [RES-004] extract dedup.

    Both rules join ``extractMethod``/``inlineMethod`` markers on
    ``blockHash`` (the content identity of the moved statements), so
    the pass is a cross-symbol join and runs before the per-symbol
    loops. A firing rule consumes the marker AND its companion
    text-level ops — the ``editStmtBlock`` on the source/host decl and
    the ``addDecl``/``deleteDecl`` of the moved declaration — so the
    disagreement surfaces exactly once, as the motion-level category."""
    def motions(stream, kind):
        return [op for op in stream if op.type == kind]

    def companions(stream, motion):
        out = [motion]
        if motion.type == "extractMethod":
            addr, decl_t = motion.params.get("newAddress"), "addDecl"
        else:
            addr, decl_t = motion.params.get("oldAddress"), "deleteDecl"
        for op in stream:
            # The motion op copied its Target verbatim from the source
            # edit, so match on BOTH ids: structural symbolIds collide
            # for same-shaped decls, and symbolId alone would swallow
            # an unrelated decl's body edit.
            if (op.type == "editStmtBlock"
                    and op.target.symbolId == motion.target.symbolId
                    and op.target.addressId == motion.target.addressId):
                out.append(op)
            elif op.type == decl_t and op.target.addressId == addr:
                out.append(op)
        return out

    # ExtractVsInline: opposite motions of the same block across sides.
    pairs = ([(e, i, "A") for e in motions(delta_a, "extractMethod")
              for i in motions(delta_b, "inlineMethod")]
             + [(e, i, "B") for e in motions(delta_b, "extractMethod")
                for i in motions(delta_a, "inlineMethod")])
    for ext, inl, side in pairs:
        if (id(ext) in (consumed_a if side == "A" else consumed_b)
                or id(inl) in (consumed_b if side == "A" else consumed_a)):
            continue
        if (not ext.params.get("blockHash")
                or ext.params.get("blockHash") != inl.params.get("blockHash")):
            continue
        # The A-stream op of the pair keys the output ordering: the
        # extract marker when A extracted ("A" side), else A's inline.
        emit(ext if side == "A" else inl,
             extract_vs_inline_conflict(ext, inl, side))
        ext_stream, ext_set = ((delta_a, consumed_a) if side == "A"
                               else (delta_b, consumed_b))
        inl_stream, inl_set = ((delta_b, consumed_b) if side == "A"
                               else (delta_a, consumed_a))
        for op in companions(ext_stream, ext):
            ext_set.add(id(op))
        for op in companions(inl_stream, inl):
            inl_set.add(id(op))

    # [RES-004]: both sides extracted the same block with identical
    # bodies (blockHash equality IS body identity — the detector only
    # fires on verbatim block membership) from the same source decl
    # INTO the same name — keep A's new declaration, drop B's
    # duplicate. Differently-named extracts are not duplicates (the
    # residual bodies call different helpers; dropping B's declaration
    # would orphan its callsite), and different bodies hash
    # differently — both keep both declarations, per the rule.
    for ea in motions(delta_a, "extractMethod"):
        if id(ea) in consumed_a:
            continue
        for eb in motions(delta_b, "extractMethod"):
            if id(eb) in consumed_b:
                continue
            if (ea.params.get("blockHash")
                    and ea.params.get("blockHash") == eb.params.get("blockHash")
                    and ea.target.symbolId == eb.target.symbolId
                    # addressId too: structural symbolIds collide for
                    # same-shaped decls, and "same source decl" must
                    # mean the same base declaration, not a shape twin.
                    and ea.target.addressId == eb.target.addressId
                    and ea.params.get("newName") == eb.params.get("newName")):
                consumed_b.add(id(eb))
                addr = eb.params.get("newAddress")
                for op in delta_b:
                    if op.type == "addDecl" and op.target.addressId == addr:
                        consumed_b.add(id(op))


def _group(ops: List[Op]) -> Dict[str, List[Op]]:
    groups: Dict[str, List[Op]] = {}
    for op in ops:
        groups.setdefault(op.target.symbolId, []).append(op)
    return groups


def divergent_move_conflict(op_a: Op, op_b: Op) -> Conflict:
    """Both sides moved the same symbol to different destinations
    ([CFR-002] "Move to different destinations")."""
    return Conflict(
        id=f"conf-{op_a.id[:8]}-{op_b.id[:8]}",
        category="DivergentMove",
        symbolId=op_a.target.symbolId,
        addressIds={"A": op_a.params.get("newAddress"),
                    "B": op_b.params.get("newAddress"),
                    "base": op_a.params.get("oldAddress")},
        opA=op_a.to_dict(),
        opB=op_b.to_dict(),
        minimalSlice={"path": "", "start": 0, "end": 0, "code": ""},
        suggestions=[
            {"id": "keepA", "label": f"Move to {op_a.params.get('newAddress')}",
             "ops": [op_a.id]},
            {"id": "keepB", "label": f"Move to {op_b.params.get('newAddress')}",
             "ops": [op_b.id]},
        ],
    )


def incompatible_signature_conflict(op_a: Op, op_b: Op) -> Conflict:
    """Both sides changed the same symbol's signature incompatibly
    ([CFR-002] "Incompatible signature changes")."""
    return Conflict(
        id=f"conf-{op_a.id[:8]}-{op_b.id[:8]}",
        category="IncompatibleSignatureChange",
        symbolId=op_a.target.symbolId,
        addressIds={"A": op_a.target.addressId, "B": op_b.target.addressId,
                    "base": None},
        opA=op_a.to_dict(),
        opB=op_b.to_dict(),
        minimalSlice={"path": "", "start": 0, "end": 0, "code": ""},
        suggestions=[
            {"id": "keepA", "label": f"Signature {op_a.params.get('newSignature')}",
             "ops": [op_a.id]},
            {"id": "keepB", "label": f"Signature {op_b.params.get('newSignature')}",
             "ops": [op_b.id]},
        ],
    )
