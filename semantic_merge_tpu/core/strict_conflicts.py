"""Strict conflict detection — the [CFR-002] categories.

The reference *requires* six conflict categories (reference
``requirements.md:93-99`` [CFR-002]) but implements exactly one,
DivergentRename, and only when the two renames surface simultaneously
at both compose cursors (reference ``semmerge/compose.py:60-70`` —
interleaved ops can mask it). This module implements the categories
expressible over the implemented op vocabulary as a full symbol-level
join, immune to interleaving:

- **DivergentRename** — both sides rename one symbol to different names.
- **DivergentMove** — both sides move one symbol to different addresses.
- **IncompatibleSignatureChange** — both sides change one symbol's
  signature differently (requires ``changeSignature`` extraction).
- **DeleteVsEdit** — one side deletes a declaration the other side
  renames / moves / re-signs / body-edits.
- **ConcurrentStmtEdit** — both sides edited one declaration's body
  to different results (requires ``editStmtBlock`` extraction —
  ``core.difflift.statement_edits``, enabled automatically in strict
  mode).

The one remaining category, extract vs inline, gates on
``extractMethod``/``inlineMethod`` extraction that no backend emits —
body-motion detection across declarations is [SPEC] in the reference
too (its requirements name the category, reference
``requirements.md:98``, but its worker has no extractor).

Semantics: conflicting ops drop from both streams (the reference's
DivergentRename drop semantics, generalized), the pre-pass runs before
composition, and the composer then finds no residual head-vs-head
conflicts. Selected via ``[engine] conflict_mode = "strict"`` or
``--strict-conflicts``; the default ``"parity"`` keeps the reference's
observable behavior bit-for-bit.

This is the host oracle of the sharded-join design: the device twin is
the same sorted self-join the TPU composer already runs for its
DivergentRename prescreen (:mod:`semantic_merge_tpu.ops.compose`),
extended with the per-category predicates — all segmented comparisons
on (symbolId-sorted) op tensors.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .conflict import (Conflict, concurrent_stmt_edit_conflict,
                       delete_vs_edit_conflict, divergent_rename_conflict)
from .ops import Op

_EDIT_TYPES = ("renameSymbol", "moveDecl", "changeSignature",
               "editStmtBlock")


def detect_conflicts_strict(delta_a: List[Op], delta_b: List[Op],
                            ) -> Tuple[List[Op], List[Op], List[Conflict]]:
    """Full-stream conflict join; returns the two streams with
    conflicting ops dropped plus the conflict records (stable order:
    by first involved A-op's stream position)."""
    by_sym_a = _group(delta_a)
    by_sym_b = _group(delta_b)

    drop_a: set = set()
    drop_b: set = set()
    conflicts: List[Conflict] = []

    for sym, ops_a in by_sym_a.items():
        ops_b = by_sym_b.get(sym)
        if not ops_b:
            continue

        ren_a = [op for op in ops_a if op.type == "renameSymbol"]
        ren_b = [op for op in ops_b if op.type == "renameSymbol"]
        for op_a in ren_a:
            for op_b in ren_b:
                if op_a.params.get("newName") != op_b.params.get("newName"):
                    conflicts.append(divergent_rename_conflict(op_a, op_b))
                    drop_a.add(id(op_a))
                    drop_b.add(id(op_b))

        mov_a = [op for op in ops_a if op.type == "moveDecl"]
        mov_b = [op for op in ops_b if op.type == "moveDecl"]
        for op_a in mov_a:
            for op_b in mov_b:
                if op_a.params.get("newAddress") != op_b.params.get("newAddress"):
                    conflicts.append(divergent_move_conflict(op_a, op_b))
                    drop_a.add(id(op_a))
                    drop_b.add(id(op_b))

        sig_a = [op for op in ops_a if op.type == "changeSignature"]
        sig_b = [op for op in ops_b if op.type == "changeSignature"]
        for op_a in sig_a:
            for op_b in sig_b:
                if op_a.params.get("newSignature") != op_b.params.get("newSignature"):
                    conflicts.append(incompatible_signature_conflict(op_a, op_b))
                    drop_a.add(id(op_a))
                    drop_b.add(id(op_b))

        stm_a = [op for op in ops_a if op.type == "editStmtBlock"]
        stm_b = [op for op in ops_b if op.type == "editStmtBlock"]
        for op_a in stm_a:
            for op_b in stm_b:
                # Same decl (same address), bodies edited to different
                # results; identical edits agree and pass through.
                if (op_a.target.addressId == op_b.target.addressId
                        and op_a.params.get("newBodyHash")
                        != op_b.params.get("newBodyHash")):
                    conflicts.append(concurrent_stmt_edit_conflict(op_a, op_b))
                    drop_a.add(id(op_a))
                    drop_b.add(id(op_b))

        del_a = [op for op in ops_a if op.type == "deleteDecl"]
        del_b = [op for op in ops_b if op.type == "deleteDecl"]
        edit_a = [op for op in ops_a if op.type in _EDIT_TYPES]
        edit_b = [op for op in ops_b if op.type in _EDIT_TYPES]
        for op_del in del_a:
            for op_edit in edit_b:
                conflicts.append(delete_vs_edit_conflict(op_del, op_edit, "A"))
                drop_a.add(id(op_del))
                drop_b.add(id(op_edit))
        for op_del in del_b:
            for op_edit in edit_a:
                conflicts.append(delete_vs_edit_conflict(op_del, op_edit, "B"))
                drop_b.add(id(op_del))
                drop_a.add(id(op_edit))

    kept_a = [op for op in delta_a if id(op) not in drop_a]
    kept_b = [op for op in delta_b if id(op) not in drop_b]
    return kept_a, kept_b, conflicts


def _group(ops: List[Op]) -> Dict[str, List[Op]]:
    groups: Dict[str, List[Op]] = {}
    for op in ops:
        groups.setdefault(op.target.symbolId, []).append(op)
    return groups


def divergent_move_conflict(op_a: Op, op_b: Op) -> Conflict:
    """Both sides moved the same symbol to different destinations
    ([CFR-002] "Move to different destinations")."""
    return Conflict(
        id=f"conf-{op_a.id[:8]}-{op_b.id[:8]}",
        category="DivergentMove",
        symbolId=op_a.target.symbolId,
        addressIds={"A": op_a.params.get("newAddress"),
                    "B": op_b.params.get("newAddress"),
                    "base": op_a.params.get("oldAddress")},
        opA=op_a.to_dict(),
        opB=op_b.to_dict(),
        minimalSlice={"path": "", "start": 0, "end": 0, "code": ""},
        suggestions=[
            {"id": "keepA", "label": f"Move to {op_a.params.get('newAddress')}",
             "ops": [op_a.id]},
            {"id": "keepB", "label": f"Move to {op_b.params.get('newAddress')}",
             "ops": [op_b.id]},
        ],
    )


def incompatible_signature_conflict(op_a: Op, op_b: Op) -> Conflict:
    """Both sides changed the same symbol's signature incompatibly
    ([CFR-002] "Incompatible signature changes")."""
    return Conflict(
        id=f"conf-{op_a.id[:8]}-{op_b.id[:8]}",
        category="IncompatibleSignatureChange",
        symbolId=op_a.target.symbolId,
        addressIds={"A": op_a.target.addressId, "B": op_b.target.addressId,
                    "base": None},
        opA=op_a.to_dict(),
        opB=op_b.to_dict(),
        minimalSlice={"path": "", "start": 0, "end": 0, "code": ""},
        suggestions=[
            {"id": "keepA", "label": f"Signature {op_a.params.get('newSignature')}",
             "ops": [op_a.id]},
            {"id": "keepB", "label": f"Signature {op_b.params.get('newSignature')}",
             "ops": [op_b.id]},
        ],
    )
