"""RGA-style ordered-list CRDT — host (oracle) implementation.

Deterministic ordering for reorderable lists (imports, params,
statement blocks). The reference implements this CRDT but never wires
it in (reference ``semmerge/crdt.py:23-57`` is dead code; its intended
plug-in points are specified at reference ``requirements.md:71-75``
[CRD-001..004] and ``architecture.md:173-178``). Here it is live — the
applier's ``reorderImports`` handler resolves order through it — and
the device twin (:mod:`semantic_merge_tpu.ops.crdt`) evaluates whole
batches of RGA materializations as segmented sorts.

Ordering semantics (identical to the reference's observable behavior):
an insert lands *before* the first element whose key tuple
``(anchor, t, author, opid)`` compares strictly greater — i.e. stable
insertion order among equal keys; ``delete`` tombstones every element
with the value; ``move`` drops the first live element with the value
and reinserts it under the new key.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List


@dataclass(frozen=True)
class Key:
    anchor: str
    t: int
    author: str
    opid: str

    def as_tuple(self) -> tuple:
        return (self.anchor, self.t, self.author, self.opid)


@dataclass
class Elem:
    key: Key
    value: str
    tombstone: bool = False


class RGA:
    def __init__(self) -> None:
        self.elems: List[Elem] = []

    def insert(self, key: Key, value: str) -> None:
        idx = len(self.elems)
        for i, elem in enumerate(self.elems):
            if key.as_tuple() < elem.key.as_tuple():
                idx = i
                break
        self.elems.insert(idx, Elem(key, value))

    def move(self, value: str, key: Key) -> None:
        for i, elem in enumerate(self.elems):
            if not elem.tombstone and elem.value == value:
                self.elems.pop(i)
                break
        self.insert(key, value)

    def delete(self, value: str) -> None:
        for elem in self.elems:
            if elem.value == value:
                elem.tombstone = True

    def materialize(self) -> List[str]:
        return [e.value for e in self.elems if not e.tombstone]
