"""Conflict data model.

JSON-shape parity with the reference conflict record (reference
``semmerge/conflict.py:10-49``), which the CLI persists as
``.semmerge-conflicts.json``. The factory reproduces the reference's
observable construction exactly: id ``conf-<a8>-<b8>``, empty minimal
slice, and keepA/keepB suggestions.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from .ops import Op

#: Version of the structured ``.semmerge-conflicts.json`` shape (the
#: object form carrying a ``resolutions`` audit block). The legacy bare
#: array — emitted whenever the resolution tier did not run — is
#: implicitly version 1 and stays byte-identical to the reference.
CONFLICTS_SCHEMA_VERSION = 2


def conflicts_payload(conflicts: Sequence,
                      resolutions: Optional[Sequence[dict]] = None):
    """The JSON payload of ``.semmerge-conflicts.json``.

    ``resolutions=None`` (the tier never ran) keeps the legacy bare
    array — reference parity and byte-identity with every pre-tier
    artifact. When the tier ran, the payload upgrades to the versioned
    object form with the full audit trail, rejected proposals
    included."""
    rows = [c.to_dict() if hasattr(c, "to_dict") else c for c in conflicts]
    if resolutions is None:
        return rows
    return {
        "schema_version": CONFLICTS_SCHEMA_VERSION,
        "conflicts": rows,
        "resolutions": list(resolutions),
    }


@dataclass
class Conflict:
    id: str
    category: str
    symbolId: str
    addressIds: Dict[str, Any]
    opA: Dict[str, Any]
    opB: Dict[str, Any]
    minimalSlice: Dict[str, Any]
    suggestions: List[Dict[str, Any]]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "category": self.category,
            "symbolId": self.symbolId,
            "addressIds": self.addressIds,
            "opA": self.opA,
            "opB": self.opB,
            "minimalSlice": self.minimalSlice,
            "suggestions": self.suggestions,
        }


def divergent_rename_conflict(op_a: Op, op_b: Op) -> Conflict:
    """Two sides renamed the same symbol to different names
    (reference ``semmerge/conflict.py:34-49``)."""
    return Conflict(
        id=f"conf-{op_a.id[:8]}-{op_b.id[:8]}",
        category="DivergentRename",
        symbolId=op_a.target.symbolId,
        addressIds={"A": op_a.target.addressId, "B": op_b.target.addressId, "base": None},
        opA=op_a.to_dict(),
        opB=op_b.to_dict(),
        minimalSlice={"path": "", "start": 0, "end": 0, "code": ""},
        suggestions=[
            {"id": "keepA", "label": f"Rename to {op_a.params.get('newName')}", "ops": [op_a.id]},
            {"id": "keepB", "label": f"Rename to {op_b.params.get('newName')}", "ops": [op_b.id]},
        ],
    )


def delete_vs_edit_conflict(op_del: Op, op_edit: Op, delete_side: str) -> Conflict:
    """One side deleted a declaration the other side edited.

    This conflict category is specified but unimplemented in the reference
    (reference ``requirements.md:93-99``); the record shape follows the
    reference's Conflict schema so tooling reads both categories uniformly.
    ``delete_side`` is ``"A"`` or ``"B"`` — which branch performed the delete.
    """
    op_a, op_b = (op_del, op_edit) if delete_side == "A" else (op_edit, op_del)
    return Conflict(
        id=f"conf-{op_a.id[:8]}-{op_b.id[:8]}",
        category="DeleteVsEdit",
        symbolId=op_del.target.symbolId,
        addressIds={"A": op_a.target.addressId, "B": op_b.target.addressId, "base": None},
        opA=op_a.to_dict(),
        opB=op_b.to_dict(),
        minimalSlice={"path": "", "start": 0, "end": 0, "code": ""},
        suggestions=[
            {"id": "keepDelete", "label": "Keep the deletion", "ops": [op_del.id]},
            {"id": "keepEdit", "label": "Keep the edit", "ops": [op_edit.id]},
        ],
    )


def concurrent_stmt_edit_conflict(op_a: Op, op_b: Op) -> Conflict:
    """Both sides edited the same declaration's statement body to
    different results ([CFR-002] "Concurrent edits to the same
    statement with overlapping token ranges", reference
    ``requirements.md:97``). Granularity is the per-decl body block —
    the unit ``editStmtBlock`` records; identical edits (equal
    ``newBodyHash``) agree and do not conflict. The minimal slice is
    the edited body itself, satisfying [CFR-003]'s minimal-code-slice
    requirement."""
    file = str(op_a.params.get("file", ""))
    return Conflict(
        id=f"conf-{op_a.id[:8]}-{op_b.id[:8]}",
        category="ConcurrentStmtEdit",
        symbolId=op_a.target.symbolId,
        addressIds={"A": op_a.target.addressId, "B": op_b.target.addressId,
                    "base": op_a.target.addressId},
        opA=op_a.to_dict(),
        opB=op_b.to_dict(),
        minimalSlice={"path": file, "start": 0, "end": 0,
                      "code": str(op_a.params.get("oldBody", ""))},
        suggestions=[
            {"id": "keepA", "label": "Keep branch A's body edit",
             "ops": [op_a.id]},
            {"id": "keepB", "label": "Keep branch B's body edit",
             "ops": [op_b.id]},
        ],
    )


def extract_vs_inline_conflict(op_extract: Op, op_inline: Op,
                               extract_side: str) -> Conflict:
    """One branch extracted a statement block into a new declaration
    while the other inlined a declaration with that same block
    ([CFR-002] "Extract vs inline on the same body", reference
    ``requirements.md:98``). The join key is ``blockHash`` — the
    content identity of the moved statements — so the motions conflict
    wherever the block lives. ``extract_side`` is ``"A"`` or ``"B"`` —
    which branch performed the extract."""
    op_a, op_b = ((op_extract, op_inline) if extract_side == "A"
                  else (op_inline, op_extract))
    return Conflict(
        id=f"conf-{op_a.id[:8]}-{op_b.id[:8]}",
        category="ExtractVsInline",
        symbolId=op_extract.target.symbolId,
        addressIds={"A": op_a.target.addressId, "B": op_b.target.addressId,
                    "base": None},
        opA=op_a.to_dict(),
        opB=op_b.to_dict(),
        minimalSlice={"path": str(op_extract.params.get("file", "")),
                      "start": 0, "end": 0,
                      "code": str(op_extract.params.get("blockHash", ""))},
        suggestions=[
            {"id": "keepExtract",
             "label": f"Keep the extracted {op_extract.params.get('newName')}",
             "ops": [op_extract.id]},
            {"id": "keepInline",
             "label": f"Keep {op_inline.params.get('methodName')} inlined",
             "ops": [op_inline.id]},
        ],
    )
