"""Deterministic identity scheme.

The reference extractor mints ``crypto.randomUUID()`` op ids and
wall-clock ISO timestamps (reference ``workers/ts/src/lift.ts:5-9``),
which makes its op logs nondeterministic and breaks its own
byte-identical-output requirement (reference ``requirements.md:163``
[NFR-DET-001]) — the compose sort key includes both fields (reference
``semmerge/compose.py:16-18``).

Here every id is a pure function of ``(seed, content, sequence number)``:

- op ids are UUID-formatted hex derived from SHA-256, so they are
  drop-in-compatible with consumers that slice them like UUIDs (the
  conflict id uses ``op.id[:8]``, reference ``semmerge/conflict.py:38``);
- timestamps are the source revision's commit time (or the epoch), not
  wall clock.

Any backend (host CPU oracle, TPU device path, a future native worker)
that derives ops from the same inputs with the same seed produces
bit-identical op logs — the parity property the BASELINE north star
demands.
"""
from __future__ import annotations

import hashlib
from typing import Any

EPOCH_ISO = "1970-01-01T00:00:00Z"


def stable_hash_hex(*parts: Any, n_hex: int = 64) -> str:
    """SHA-256 over the ``|``-joined string forms of *parts*."""
    payload = "|".join(str(p) for p in parts)
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()[:n_hex]


def deterministic_op_id(seed: str, *content: Any) -> str:
    """A UUID-shaped (8-4-4-4-12) deterministic id."""
    h = stable_hash_hex(seed, *content, n_hex=32)
    return f"{h[0:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:32]}"


def stable_hash64(*parts: Any) -> int:
    """First 64 bits of the SHA-256, as a Python int in [0, 2**64)."""
    return int(stable_hash_hex(*parts, n_hex=16), 16)


def symbol_id_from_signature(sig: str) -> str:
    """SymbolId = first 16 hex chars of sha256(structural signature).

    Identical to the reference's scheme (reference
    ``workers/ts/src/sast.ts:69-71,96``); exactly 64 bits, so device code
    can carry symbol ids losslessly as int64 lanes.
    """
    return hashlib.sha256(sig.encode("utf-8")).hexdigest()[:16]
