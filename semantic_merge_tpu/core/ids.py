"""Deterministic identity scheme.

The reference extractor mints ``crypto.randomUUID()`` op ids and
wall-clock ISO timestamps (reference ``workers/ts/src/lift.ts:5-9``),
which makes its op logs nondeterministic and breaks its own
byte-identical-output requirement (reference ``requirements.md:163``
[NFR-DET-001]) — the compose sort key includes both fields (reference
``semmerge/compose.py:16-18``).

Here every id is a pure function of ``(seed, content, sequence number)``:

- op ids are UUID-formatted hex derived from SHA-256, so they are
  drop-in-compatible with consumers that slice them like UUIDs (the
  conflict id uses ``op.id[:8]``, reference ``semmerge/conflict.py:38``);
- timestamps are the source revision's commit time (or the epoch), not
  wall clock.

Any backend (host CPU oracle, TPU device path, a future native worker)
that derives ops from the same inputs with the same seed produces
bit-identical op logs — the parity property the BASELINE north star
demands.
"""
from __future__ import annotations

import functools
import hashlib
from typing import Any

from .ops import OP_TYPES

EPOCH_ISO = "1970-01-01T00:00:00Z"


def stable_hash_hex(*parts: Any, n_hex: int = 64) -> str:
    """SHA-256 over the ``|``-joined string forms of *parts*."""
    payload = "|".join(str(p) for p in parts)
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()[:n_hex]


#: Stable 1-byte code per schema op type (OP_TYPES is schema-ordered and
#: append-only). The device diff kinds 0-3 coincide with the first four.
_TYPE_CODE = {t: i for i, t in enumerate(OP_TYPES)}
# Load-bearing: the device hashes clip(kind, 0, 3) straight into the id
# payload (ops/fused._op_id_words), so the KIND_* codes MUST stay equal
# to these type codes — reordering OP_TYPES would silently fork ids.
# Checked unconditionally (not `assert`): `python -O` must not strip it.
if [_TYPE_CODE[t] for t in
        ("renameSymbol", "moveDecl", "addDecl", "deleteDecl")] != [0, 1, 2, 3]:
    raise AssertionError(
        "OP_TYPES order changed: device KIND_* codes no longer match the "
        "first four op-type codes; op ids would silently fork")


@functools.lru_cache(maxsize=4096)
def op_id_prefix_digest(seed: str, rev: str) -> bytes:
    """16-byte digest of the (seed, rev) pair — the per-merge-side
    constant prefix of every op-id payload.

    Length-prefixing the seed makes the encoding injective: the v1
    ``f"{seed}|{rev}"`` form collided ("a|b","c") with ("a","b|c").
    This is id scheme v2 (changes every op id vs v1; nothing pins v1
    hex values — parity is host↔device, and both call this)."""
    seed_b = seed.encode("utf-8")
    payload = len(seed_b).to_bytes(4, "big") + seed_b + rev.encode("utf-8")
    return hashlib.sha256(payload).digest()[:16]


@functools.lru_cache(maxsize=262144)
def value_digest10(s: str) -> bytes:
    """80-bit value hash of a string (``b"\\0"*10`` for the empty
    string / absent value). Cached: symbol/address/file strings repeat
    across the tens of thousands of ops of a large merge, and the
    device path ships exactly these digests in its hash table."""
    if not s:
        return b"\0" * 10
    return hashlib.sha256(s.encode("utf-8")).digest()[:10]


def deterministic_op_id(seed: str, rev: str = "", idx: int = 0,
                        op_type: str = "", sym: str = "",
                        a_addr: str = "", b_addr: str = "") -> str:
    """A UUID-shaped (8-4-4-4-12) deterministic id.

    SHA-256 over ONE fixed 51-byte payload: ``prefix_digest(seed, rev)
    (16) ‖ idx be32 (4) ‖ type code (1) ‖ h80(sym) ‖ h80(aAddr) ‖
    h80(bAddr)``. Fixed width keeps the device twin to a single SHA
    block with no byte-assembly gathers (the variable-length ASCII
    payload of the v1 scheme was ~2/3 of the fused kernel's compute);
    the 80-bit string digests keep collision odds negligible at
    repo-scale string counts. Identity properties are unchanged: ids
    are pure functions of (seed, rev, index, type, symbol, addresses).
    """
    payload = (op_id_prefix_digest(seed, rev)
               + int(idx).to_bytes(4, "big")
               + bytes([_TYPE_CODE.get(op_type, 255)])
               + value_digest10(sym) + value_digest10(a_addr)
               + value_digest10(b_addr))
    h = hashlib.sha256(payload).hexdigest()[:32]
    return f"{h[0:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:32]}"


def stable_hash64(*parts: Any) -> int:
    """First 64 bits of the SHA-256, as a Python int in [0, 2**64)."""
    return int(stable_hash_hex(*parts, n_hex=16), 16)


def symbol_id_from_signature(sig: str) -> str:
    """SymbolId = first 16 hex chars of sha256(structural signature).

    Identical to the reference's scheme (reference
    ``workers/ts/src/sast.ts:69-71,96``); exactly 64 bits, so device code
    can carry symbol ids losslessly as int64 lanes.
    """
    return hashlib.sha256(sig.encode("utf-8")).hexdigest()[:16]
