"""Host (oracle) implementation of op-log composition.

Deterministic two-way composition of the op logs of branches A and B.
Observable semantics are bit-for-bit those of the reference composer
(reference ``semmerge/compose.py:11-114``), which the device
implementation (:mod:`semantic_merge_tpu.ops.compose`) must match:

- Each log is sorted by ``(type precedence, provenance.timestamp, id)``
  and the two sorted streams are merged two-pointer style. The
  cross-stream pick compares ``(precedence, timestamp)`` only, ties
  taken from A. Rationale: the reference's key includes the op id
  (reference ``semmerge/compose.py:16-18``), but its ids are random
  uuids and its timestamps wall-clock — in practice the left log is
  lifted before the right one, so left ops carry earlier timestamps
  and surface first. With deterministic ids and a shared per-merge
  timestamp, comparing ids across streams would turn that into a hash
  coin-flip — e.g. whether branch B's real ``moveDecl`` or branch A's
  spurious rename-induced ``moveDecl`` (addressId embeds the name)
  lands last in the move chain, flipping the merge result. A-before-B
  on ties reproduces the reference's observed ordering, always.
- A *DivergentRename* conflict is detected **only head-vs-head**: when
  the current heads of both streams are ``renameSymbol`` ops on the same
  symbol with different new names, a conflict is emitted and *both* ops
  are dropped (no chain updates, nothing materialized). Interleaved
  unrelated ops can mask a divergent rename — a reference quirk kept in
  parity mode.
- ``renameSymbol`` records ``symbolId → newName`` in the rename chain;
  ``moveDecl`` merges ``newAddress`` / ``newFile`` (falling back to
  ``params["file"]``) per symbol into the move chain.
- Materialization clones the op, then: retargets ``target.addressId``
  to the chained ``newAddress``; rewrites a ``moveDecl``'s own params to
  the chained destination; rewrites a ``renameSymbol``'s ``file`` (and
  ``newFile``) to the chained file; and tags non-rename ops on renamed
  symbols with ``renameContext``. The current op's own chain
  contribution is visible to itself (a move sees its own destination).
"""
from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Dict, List, Tuple

from .conflict import Conflict, divergent_rename_conflict
from .ops import Op, Target


def compose_oplogs(delta_a: List[Op], delta_b: List[Op]) -> Tuple[List[Op], List[Conflict]]:
    from ..obs import spans as obs_spans
    with obs_spans.span("compose_oplogs", layer="ops",
                        n_a=len(delta_a), n_b=len(delta_b)):
        return _compose_oplogs(delta_a, delta_b)


def _compose_oplogs(delta_a: List[Op], delta_b: List[Op]) -> Tuple[List[Op], List[Conflict]]:
    ops_a = sorted(delta_a, key=Op.sort_key)
    ops_b = sorted(delta_b, key=Op.sort_key)

    # Conflict detection is the cursor walk below — factored out so the
    # fused device path replays the *same* implementation. Dropping the
    # conflicted pairs first and then running a plain two-pointer merge
    # is take-order-identical to the reference's single interleaved
    # loop: a conflict advances both cursors without emitting, and the
    # pairwise (precedence, timestamp) comparisons that order the
    # remaining ops never depend on the dropped neighbors.
    conflicts, dropped_a, dropped_b = cursor_walk_conflicts(ops_a, ops_b)
    stream_a = [op for i, op in enumerate(ops_a) if i not in dropped_a]
    stream_b = [op for i, op in enumerate(ops_b) if i not in dropped_b]

    out: List[Op] = []
    rename_chain: Dict[str, str] = {}
    move_chain: Dict[str, Dict[str, str]] = {}

    ia = ib = 0
    while ia < len(stream_a) or ib < len(stream_b):
        a_head = stream_a[ia] if ia < len(stream_a) else None
        b_head = stream_b[ib] if ib < len(stream_b) else None
        take_a = a_head is not None and (
            b_head is None or a_head.sort_key()[:2] <= b_head.sort_key()[:2]
        )
        op = a_head if take_a else b_head
        assert op is not None

        if op.type == "renameSymbol":
            rename_chain[op.target.symbolId] = str(op.params.get("newName"))
        elif op.type == "moveDecl":
            entry = dict(move_chain.get(op.target.symbolId, {}))
            new_addr = op.params.get("newAddress")
            new_file = op.params.get("newFile") or op.params.get("file")
            if new_addr is not None:
                entry["newAddress"] = str(new_addr)
            if new_file is not None:
                entry["newFile"] = str(new_file)
            if entry:
                move_chain[op.target.symbolId] = entry

        out.append(_materialize(op, rename_chain, move_chain))
        if take_a:
            ia += 1
        else:
            ib += 1

    return out, conflicts


def cursor_walk_conflicts(ops_a: List[Op], ops_b: List[Op],
                          keys_a=None, keys_b=None
                          ) -> Tuple[List[Conflict], set, set]:
    """The head-vs-head DivergentRename walk alone, over *already
    canonically sorted* streams: returns ``(conflicts, dropped_a,
    dropped_b)`` where the drop sets hold positions into the sorted
    streams. Chain state never influences detection, so the walk
    separates cleanly from materialization — the fused device path
    (:mod:`semantic_merge_tpu.ops.fused`) composes speculatively with
    no drops in O(log n) on device, then runs this exact sequential
    oracle on host only when its parallel candidate join fired, and
    patches the affected symbols. Same quirks as
    :func:`compose_oplogs`: detection only when both heads surface
    simultaneously, both ops dropped, interleavings can mask.

    ``keys_a``/``keys_b`` optionally inject the per-op cross-stream
    comparison keys (any ordered type, same semantics as
    ``op.sort_key()[:2]``). The fused caller derives them vectorized
    from its device kind columns — every op of one fused merge shares
    one timestamp, so the key collapses to the precedence int and the
    ~50k Python ``sort_key`` calls disappear."""
    conflicts: List[Conflict] = []
    dropped_a: set = set()
    dropped_b: set = set()
    # Keys precomputed once — the loop runs per op over merges that can
    # hold tens of thousands of ops.
    if (keys_a is None) != (keys_b is None):
        raise ValueError("inject both keys_a and keys_b or neither "
                         "(mixed key types do not compare)")
    if keys_a is None:
        keys_a = [op.sort_key()[:2] for op in ops_a]
        keys_b = [op.sort_key()[:2] for op in ops_b]
    elif len(keys_a) != len(ops_a) or len(keys_b) != len(ops_b):
        raise ValueError("injected keys must align 1:1 with the sorted streams")
    na, nb = len(ops_a), len(ops_b)
    ia = ib = 0
    while ia < na or ib < nb:
        a_head = ops_a[ia] if ia < na else None
        b_head = ops_b[ib] if ib < nb else None
        # A conflict can only fire when BOTH heads are renameSymbol, so
        # any run of takes against a non-rename (or exhausted) opposite
        # head is conflict-free and bulk-advances via bisect over the
        # sorted keys — observably identical to stepping one op at a
        # time, at O(log run) instead of O(run). On a 10k-file merge
        # only the rename-vs-rename interleavings walk singly.
        if b_head is None or b_head.type != "renameSymbol":
            if a_head is None:  # only B remains; nothing can conflict
                ib = nb
            elif b_head is None:
                ia = na
            else:
                # take_a holds while keys_a[ia] <= keys_b[ib].
                nxt = bisect_right(keys_a, keys_b[ib], ia, na)
                if nxt == ia:
                    ib += 1  # A's head outranks B's: single take from B
                else:
                    ia = nxt
            continue
        if a_head is None or a_head.type != "renameSymbol":
            if a_head is None:
                ib = nb
            else:
                # take_b holds while keys_b[ib] < keys_a[ia].
                nxt = bisect_left(keys_b, keys_a[ia], ib, nb)
                if nxt == ib:
                    ia += 1  # B's head is not taken next: take from A
                else:
                    ib = nxt
            continue
        take_a = keys_a[ia] <= keys_b[ib]
        op = a_head if take_a else b_head
        other = b_head if take_a else a_head
        if (
            op.type == "renameSymbol"
            and other.type == "renameSymbol"
            and op.target.symbolId == other.target.symbolId
            and op.params.get("newName") != other.params.get("newName")
        ):
            conflicts.append(divergent_rename_conflict(a_head, b_head))
            dropped_a.add(ia)
            dropped_b.add(ib)
            ia += 1
            ib += 1
            continue
        if take_a:
            ia += 1
        else:
            ib += 1
    return conflicts, dropped_a, dropped_b


def _materialize(op: Op, rename_chain: Dict[str, str],
                 move_chain: Dict[str, Dict[str, str]]) -> Op:
    sym = op.target.symbolId
    if move_chain.get(sym) is None and (
            sym not in rename_chain or op.type == "renameSymbol"):
        # No chain rewrite applies: the composed stream reuses the input
        # op unchanged. Composed ops are treated as immutable downstream
        # (JSON-observable output is identical to cloning, which the
        # reference does unconditionally — semmerge/compose.py:117-127).
        return op
    cloned = op.clone()
    moved = move_chain.get(sym)
    if moved is not None:
        new_addr = moved.get("newAddress")
        new_file = moved.get("newFile")
        if cloned.type == "moveDecl":
            if new_addr is not None:
                cloned.params["newAddress"] = new_addr
            if new_file is not None:
                cloned.params["newFile"] = new_file
        if new_addr is not None:
            cloned.target = Target(symbolId=sym, addressId=new_addr)
        if cloned.type == "renameSymbol" and new_file is not None:
            cloned.params["newFile"] = new_file
            cloned.params["file"] = new_file
    if sym in rename_chain and cloned.type != "renameSymbol":
        cloned.params = {**cloned.params, "renameContext": rename_chain[sym]}
    return cloned
