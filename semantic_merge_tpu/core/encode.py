"""String interning and tensor encoding for the device pipeline.

The merge problem is string-heavy (symbol ids, addresses, names, file
paths, timestamps) but every device operation only needs *equality* or
*order* on those strings — never their bytes. So the host interns
strings to dense int32 ids once per merge and ships struct-of-arrays
int32 tensors to the device; results decode back through the same
table. Two interning modes:

- :class:`Interner` — equality-preserving, insertion-ordered. Used for
  join keys (symbolId, addressId, name, file).
- :func:`rank_intern` — order-preserving: ids are the ranks of the
  sorted unique strings, so integer comparison equals lexicographic
  string comparison. Used for compose sort keys (timestamp, op id),
  where the reference's semantics are defined by Python tuple
  comparison over strings (reference ``semmerge/compose.py:16-18``).

Sentinel ``NULL_ID = -1`` encodes absent values (e.g. a
VariableStatement's null name).
"""
from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Sequence

import numpy as np

NULL_ID = -1
#: int32 sentinel greater than any interned id — used as padding so
#: padded slots sort to the end.
PAD_ID = np.int32(2**31 - 1)


class Interner:
    """Insertion-ordered string→int32 interner.

    ``token`` is a process-unique id for *this* interner instance —
    cache keys that embed encoded ids must include it, since ids are
    only meaningful relative to one interner's history.

    Interning is thread-safe: the hit path is a lock-free dict read
    (GIL-atomic), a miss takes the instance lock with a re-check.
    Published ids are always valid ``strings`` indices (the append
    happens before the id becomes visible). ``shared = True`` marks a
    process-shared instance (the warm-residency backend interner):
    :meth:`object_table` then returns a defensive copy, because a view
    handed to one thread is invalidated when another thread's later
    call syncs new strings over the view's trailing ``None`` slot."""

    _token_counter = itertools.count()

    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}
        self.strings: List[str] = []
        self._obj: np.ndarray | None = None
        self._obj_n = 0
        self.token = next(Interner._token_counter)
        self.shared = False
        self._lock = threading.Lock()

    def object_table(self) -> np.ndarray:
        """Numpy object-array mirror ``[*strings, None]`` with amortized
        (geometric) growth: fancy-indexing an int32 id column against it
        wraps ``NULL_ID`` (−1) to the trailing ``None``, so a whole
        column of interned ids decodes in one vectorized gather instead
        of a Python loop — and long-lived interners (the device backend
        keeps one across merges) don't rebuild the mirror per merge.

        The result is a read-only VIEW of the cached buffer: the next
        ``intern()`` may overwrite its trailing ``None`` slot (and
        later slots). Gather from it immediately; never hold it across
        interning. Writes through the view raise — callers that need a
        mutable decode must copy. A ``shared`` interner returns a copy
        instead (another thread's call may re-sync under the view)."""
        with self._lock:
            n = len(self.strings)
            if self._obj is None or n + 1 > len(self._obj):
                grown = np.empty((max(64, 2 * (n + 1)),), dtype=object)
                grown[:n] = self.strings
                self._obj = grown
                self._obj_n = n
            elif n > self._obj_n:
                self._obj[self._obj_n:n] = self.strings[self._obj_n:n]
                self._obj_n = n
            self._obj[n] = None  # reset: growth may have written here
            view = self._obj[:n + 1]
            if self.shared:
                return view.copy()
            view.flags.writeable = False
            return view

    def intern(self, s: str | None) -> int:
        if s is None:
            return NULL_ID
        got = self._ids.get(s)
        if got is not None:
            return got
        with self._lock:
            got = self._ids.get(s)
            if got is not None:
                return got
            new_id = len(self.strings)
            # Append BEFORE publishing the id: any thread that can see
            # the id can index ``strings`` with it.
            self.strings.append(s)
            self._ids[s] = new_id
            return new_id

    def lookup(self, idx: int) -> str | None:
        if idx == NULL_ID:
            return None
        return self.strings[idx]

    def __len__(self) -> int:
        return len(self.strings)


def equality_key(value) -> str | None:
    """A string key whose equality matches Python ``==`` on op-param
    values. The host conflict check compares raw ``params.get("newName")``
    values (reference ``semmerge/compose.py:66``), where ``1 == 1.0 ==
    True`` but ``1 != "1"`` — plain ``str()`` interning would merge the
    latter. Numbers map to their exact rational value, strings are
    tagged, everything else falls back to a type-tagged canonical repr.
    """
    if value is None:
        return None
    if isinstance(value, (bool, int, float)):
        import fractions
        import math
        if isinstance(value, float) and not math.isfinite(value):
            return f"float:{value!r}:{id(value)}"  # NaN != NaN → never equal
        return f"num:{fractions.Fraction(value)}"
    if isinstance(value, str):
        return f"str:{value}"
    try:
        import json
        return f"obj:{json.dumps(value, sort_keys=True, separators=(',', ':'))}"
    except (TypeError, ValueError):
        return f"repr:{type(value).__name__}:{value!r}"


def rank_intern(values: Sequence[str | None]) -> tuple[np.ndarray, List[str]]:
    """Order-preserving interning: returns per-value ranks (int32,
    ``NULL_ID`` for None) and the sorted unique table."""
    uniq = sorted({v for v in values if v is not None})
    ranks = {s: i for i, s in enumerate(uniq)}
    out = np.asarray([NULL_ID if v is None else ranks[v] for v in values], dtype=np.int32)
    return out, uniq


@dataclass
class DeclTensor:
    """A scanned snapshot as device-ready arrays (one row per decl,
    document order — the order the differ's map semantics key off)."""

    sym: np.ndarray    # int32 interned symbolId
    addr: np.ndarray   # int32 interned addressId
    name: np.ndarray   # int32 interned name, NULL_ID when anonymous
    file: np.ndarray   # int32 interned file path
    n: int

    @staticmethod
    def empty() -> "DeclTensor":
        z = np.zeros((0,), dtype=np.int32)
        return DeclTensor(z, z, z, z, 0)


def encode_decls(nodes, interner: Interner) -> DeclTensor:
    """Encode scanner output (``DeclNode`` list) with a shared interner."""
    n = len(nodes)
    sym = np.empty(n, dtype=np.int32)
    addr = np.empty(n, dtype=np.int32)
    name = np.empty(n, dtype=np.int32)
    file_ = np.empty(n, dtype=np.int32)
    for i, node in enumerate(nodes):
        sym[i] = interner.intern(node.symbolId)
        addr[i] = interner.intern(node.addressId)
        name[i] = interner.intern(node.name)
        file_[i] = interner.intern(node.file)
    return DeclTensor(sym=sym, addr=addr, name=name, file=file_, n=n)


def encode_decls_keyed(keyed_nodes, interner: Interner, cache=None
                       ) -> tuple[DeclTensor, list]:
    """Encode per-file scan groups (from
    :func:`semantic_merge_tpu.frontend.scanner.scan_snapshot_keyed`)
    with per-file column caching.

    Within one 3-way merge the base/left/right snapshots share almost
    every file, and repeated merges re-encode mostly-unchanged trees —
    caching the encoded int32 columns per (file identity, interner)
    turns ~100k ``intern`` calls at the 1k-file bench rung into array
    concatenation. Entries are keyed by the scan identity *plus* the
    interner's token, so a different/reset interner can never read
    stale ids. Returns ``(tensor, flat node list)``.
    """
    parts_sym: list = []
    parts_addr: list = []
    parts_name: list = []
    parts_file: list = []
    flat: list = []
    n = 0
    for key, nodes in keyed_nodes:
        flat.extend(nodes)
        if not nodes:
            continue
        ckey = (("enc", interner.token) + tuple(key[1:])
                if cache is not None and key is not None else None)
        arrs = cache.get(ckey) if ckey is not None else None
        if arrs is None:
            t = encode_decls(nodes, interner)
            arrs = (t.sym, t.addr, t.name, t.file)
            if ckey is not None:
                cache.put(ckey, arrs, size=4 * t.sym.nbytes + 64)
        parts_sym.append(arrs[0])
        parts_addr.append(arrs[1])
        parts_name.append(arrs[2])
        parts_file.append(arrs[3])
        n += len(arrs[0])
    if not n:
        return DeclTensor.empty(), flat
    return DeclTensor(
        sym=np.concatenate(parts_sym), addr=np.concatenate(parts_addr),
        name=np.concatenate(parts_name), file=np.concatenate(parts_file),
        n=n), flat


def pad_to(arr: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full((size,), fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def bucket_size(n: int, minimum: int = 8) -> int:
    """Smallest of ``{2^k, 3·2^(k-1)}`` ≥ ``n`` (and ≥ ``minimum``).

    Still logarithmically many compiled shapes, but the half-step
    ladder caps padding waste at 1/3 instead of 1/2: a 23k-op stream
    pads to 24 576 rather than 32 768, and every device sort, scan,
    hash and fetch over the axis shrinks proportionally (~25% at the
    10k-file bench rung, where both the decl and op axes land just
    above a power of two)."""
    size = minimum
    while size < n:
        half = size + size // 2
        if half >= n and size % 2 == 0:
            return half
        size *= 2
    return size


def shard_ranges(n: int, rows_per_shard: int) -> list[tuple[int, int]]:
    """Split ``n`` rows into contiguous ``(lo, hi)`` ranges of at most
    ``rows_per_shard`` rows — the host-tail pipeline's shard plan. The
    split is a pure function of ``(n, rows_per_shard)`` so every
    consumer (chain decode, op materialization, shard serialization)
    agrees on shard boundaries, and the deterministic shard-order merge
    of per-shard results reproduces the serial output byte-for-byte.
    ``n = 0`` yields no shards (the empty-stream fast paths)."""
    if n <= 0:
        return []
    rows = max(1, int(rows_per_shard))
    return [(lo, min(lo + rows, n)) for lo in range(0, n, rows)]


def shard_bucket(n: int, k: int = 1) -> int:
    """Bucket that divides evenly into ``k`` shards: ``k`` × a ladder
    value ≥ ceil(n/k), at least 8 rows total. For ``k = 1`` this equals
    :func:`bucket_size`; for any ``k`` (including non-powers-of-two,
    e.g. a 6-device mesh) the padded axis is divisible by ``k`` while
    the set of compiled shapes stays logarithmic in ``n``. The ≥8-row
    floor is folded into the ladder lookup so the result is always an
    on-ladder multiple of ``k`` and monotonic in ``n``."""
    per = bucket_size(max((n + k - 1) // k, (8 + k - 1) // k), minimum=1)
    return k * per


# --- op-tensor encoding (compose input/output) ------------------------------

#: Op-kind codes for device columns. Only kinds the differ emits get
#: dedicated lift columns, but compose carries any kind via precedence.
OP_KIND_CODES: Dict[str, int] = {
    "renameSymbol": 0,
    "moveDecl": 1,
    "addDecl": 2,
    "deleteDecl": 3,
}


@dataclass
class OpTensor:
    """An op log as struct-of-arrays int32 columns.

    ``prec``/``ts_rank``/``id_rank`` are the compose sort key; the
    param columns cover the fields compose reads or rewrites
    (reference ``semmerge/compose.py:30-49,71-82``). ``op_index``
    points back into the source ``List[Op]`` for decode.
    """

    prec: np.ndarray       # precedence of op type
    ts_rank: np.ndarray    # order-interned provenance.timestamp
    id_rank: np.ndarray    # order-interned op id
    is_rename: np.ndarray  # int32 0/1
    is_move: np.ndarray    # int32 0/1
    sym: np.ndarray        # interned target.symbolId
    new_name: np.ndarray   # interned equality_key(params.newName) or NULL —
    #   the DivergentRename comparison value (Python == semantics)
    chain_name: np.ndarray  # interned str(params.newName) for renames —
    #   the rename-chain value; distinct from new_name because the
    #   reference stores str(None) == "None" in the chain while the
    #   conflict check compares the raw None (semmerge/compose.py:66,72)
    new_addr: np.ndarray   # interned str(params.newAddress) or NULL
    chain_file: np.ndarray  # interned str(params.newFile or params.file) —
    #   the move-chain file contribution with host truthiness semantics
    #   (semmerge/compose.py:76: falsy newFile falls back to file)
    op_index: np.ndarray   # row → index in the source op list
    n: int


def encode_oplog(ops, interner: Interner, ts_table: Dict[str, int],
                 id_table: Dict[str, int]) -> OpTensor:
    """Encode a ``List[Op]``. ``ts_table``/``id_table`` are
    order-preserving rank maps built over *both* logs being composed."""
    from .ops import OP_PRECEDENCE, UNKNOWN_PRECEDENCE

    n = len(ops)
    cols = {k: np.empty(n, dtype=np.int32) for k in
            ("prec", "ts_rank", "id_rank", "is_rename", "is_move", "sym",
             "new_name", "chain_name", "new_addr", "chain_file", "op_index")}
    for i, op in enumerate(ops):
        ts = str(op.provenance.get("timestamp", "1970-01-01T00:00:00Z"))
        cols["prec"][i] = OP_PRECEDENCE.get(op.type, UNKNOWN_PRECEDENCE)
        cols["ts_rank"][i] = ts_table[ts]
        cols["id_rank"][i] = id_table[op.id]
        cols["is_rename"][i] = 1 if op.type == "renameSymbol" else 0
        cols["is_move"][i] = 1 if op.type == "moveDecl" else 0
        cols["sym"][i] = interner.intern(op.target.symbolId)
        p = op.params
        new_name = p.get("newName")
        cols["new_name"][i] = interner.intern(equality_key(new_name))
        cols["chain_name"][i] = (interner.intern(str(new_name))
                                 if op.type == "renameSymbol" else NULL_ID)
        new_addr = p.get("newAddress")
        cols["new_addr"][i] = interner.intern(str(new_addr)) if new_addr is not None else NULL_ID
        file_contrib = p.get("newFile") or p.get("file")
        cols["chain_file"][i] = (interner.intern(str(file_contrib))
                                 if file_contrib is not None else NULL_ID)
        cols["op_index"][i] = i
    return OpTensor(n=n, **cols)


def build_rank_tables(ops_a, ops_b) -> tuple[Dict[str, int], Dict[str, int]]:
    """Order-preserving rank maps for (timestamp, id) across both logs."""
    timestamps = set()
    ids = set()
    for op in [*ops_a, *ops_b]:
        timestamps.add(str(op.provenance.get("timestamp", "1970-01-01T00:00:00Z")))
        ids.add(op.id)
    ts_table = {s: i for i, s in enumerate(sorted(timestamps))}
    id_table = {s: i for i, s in enumerate(sorted(ids))}
    return ts_table, id_table
