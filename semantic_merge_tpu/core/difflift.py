"""Declaration diffing and op lifting — host (oracle) implementation.

Reproduces the reference worker's diff/lift stage exactly
(reference ``workers/ts/src/diff.ts:5-31`` and
``workers/ts/src/lift.ts:11-66``), with the nondeterministic identity
fields (uuid4 ids, wall-clock timestamps) replaced by the seeded scheme
from :mod:`semantic_merge_tpu.core.ids`.

Diff semantics (parity-critical quirks included):

- Both node lists collapse into symbolId-keyed maps with JS ``Map``
  semantics: iteration follows *first* insertion order, but a duplicate
  symbolId keeps the *last* node (coarse signatures like ``class{2}``
  collide by design; reference ``implementation.md:1309`` acknowledges
  last-wins).
- Per base symbol, in map order: absent on the side → ``delete``;
  differing addressId → ``move``; differing non-null names → ``rename``
  (a symbol can emit both move and rename).
- Per side *list* entry (not map — duplicates emit repeatedly): symbolId
  absent in base → ``add``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

from ..frontend.scanner import DeclNode
from .ids import EPOCH_ISO, deterministic_op_id
from .ops import Op, Target


@dataclass
class Diff:
    kind: str  # "rename" | "move" | "add" | "delete" | "changeSig"
    a: DeclNode | None = None
    b: DeclNode | None = None


def diff_nodes(base: List[DeclNode], side: List[DeclNode]) -> List[Diff]:
    base_map: Dict[str, DeclNode] = {}
    for n in base:
        base_map[n.symbolId] = n  # dict: first-insert order, last value wins
    side_map: Dict[str, DeclNode] = {}
    for n in side:
        side_map[n.symbolId] = n

    diffs: List[Diff] = []
    for sid, bnode in base_map.items():
        snode = side_map.get(sid)
        if snode is None:
            diffs.append(Diff("delete", a=bnode))
            continue
        if bnode.addressId != snode.addressId:
            diffs.append(Diff("move", a=bnode, b=snode))
        if bnode.name and snode.name and bnode.name != snode.name:
            diffs.append(Diff("rename", a=bnode, b=snode))
    for snode in side:
        if snode.symbolId not in base_map:
            diffs.append(Diff("add", b=snode))
    return diffs


def refine_signature_changes(diffs: List[Diff], sources=None,
                             matcher=None) -> List[Diff]:
    """Fold residual ``delete``+``add`` pairs into ``changeSig`` diffs.

    Editing a function's parameter or return types changes its
    structural symbolId, so the exact-key join reports the decl as
    deleted-and-re-added; the ``changeSig`` diff kind exists so such
    edits can merge as one signature change instead. This pass produces
    it: a deleted base decl and an added side decl that share
    ``(file, name, kind)`` (names non-null) are the same declaration
    with a changed signature.

    With ``matcher`` (an
    :class:`semantic_merge_tpu.models.signature.EmbeddingSignatureMatcher`)
    and ``sources`` (a :func:`source_maps` pair), a second pass scores
    the *residual* deletes/adds — declarations that were renamed AND
    retyped, which no key can pair — by embedding similarity.

    Deterministic pairing: the k-th delete with a given key pairs with
    the k-th add with that key; model pairs break ties by score then
    stream position. The ``changeSig`` takes the delete's position in
    the stream; the paired add is dropped (later op ids re-index, which
    is why this pass must run identically in every backend — it is
    opt-in precisely because parity-with-reference mode must keep the
    delete+add shape).
    """
    # Pass 1: pair each eligible delete (stream order) with the next
    # unconsumed eligible add sharing its key.
    pending_adds: Dict[tuple, List[int]] = {}
    for idx, d in enumerate(diffs):
        if d.kind == "add" and d.b is not None and d.b.name:
            pending_adds.setdefault((d.b.file, d.b.name, d.b.kind), []).append(idx)
    paired: Dict[int, int] = {}  # delete idx -> add idx
    consumed: set = set()
    for idx, d in enumerate(diffs):
        if d.kind == "delete" and d.a is not None and d.a.name:
            queue = pending_adds.get((d.a.file, d.a.name, d.a.kind))
            if queue:
                add_idx = queue.pop(0)
                paired[idx] = add_idx
                consumed.add(add_idx)

    # Pass 1b: model-scored pairing of the residuals.
    if matcher is not None and sources is not None:
        base_map, side_map = sources
        # Candidates are keyed by (kind, file): a changeSignature op's
        # structured-apply spans are base offsets in the delete's file,
        # so a cross-file pair could never materialize correctly — a
        # decl moved AND retyped stays delete+add.
        res_del: List[int] = []
        del_items: List[tuple] = []
        for idx, d in enumerate(diffs):
            if (d.kind == "delete" and idx not in paired
                    and d.a is not None and d.a.name):
                src = base_map.get(d.a.file)
                if src is not None:
                    res_del.append(idx)
                    del_items.append(((d.a.kind, d.a.file),
                                      src[d.a.pos:d.a.end]))
        res_add: List[int] = []
        add_items: List[tuple] = []
        for idx, d in enumerate(diffs):
            if (d.kind == "add" and idx not in consumed
                    and d.b is not None and d.b.name):
                src = side_map.get(d.b.file)
                if src is not None:
                    res_add.append(idx)
                    add_items.append(((d.b.kind, d.b.file),
                                      src[d.b.pos:d.b.end]))
        for di, aj in matcher.pair(del_items, add_items):
            paired[res_del[di]] = res_add[aj]
            consumed.add(res_add[aj])

    # Pass 2: rebuild the stream.
    out: List[Diff] = []
    for idx, d in enumerate(diffs):
        if idx in paired:
            out.append(Diff("changeSig", a=d.a, b=diffs[paired[idx]].b))
        elif idx not in consumed:
            out.append(d)
    return out


def source_maps(base_files, side_files) -> tuple:
    """(base, side) path→content maps for structured-apply payloads."""
    from ..frontend.scanner import normalize_path
    return ({normalize_path(f["path"]): f["content"] for f in base_files},
            {normalize_path(f["path"]): f["content"] for f in side_files})


def _decl_payload(d: Diff, sources) -> Dict | None:
    """Structured-apply payload for an op's ``effects``.

    Spans are *base-content* offsets (``pos`` is the decl's full start,
    ``end`` its last token), texts are side-content slices — exactly
    what the applier needs to splice without re-parsing. This is the
    designed-but-unbuilt worker ``applyOps`` stage (reference
    ``implementation.md:1258,1339``), opt-in because it extends the
    reference's op JSON shape.
    """
    if sources is None:
        return None
    base_map, side_map = sources
    if d.kind == "add" and d.b is not None:
        src = side_map.get(d.b.file)
        if src is not None:
            return {"text": src[d.b.pos:d.b.end]}
    elif d.kind == "delete" and d.a is not None:
        return {"start": d.a.pos, "end": d.a.end}
    elif d.kind == "changeSig" and d.a is not None and d.b is not None:
        src = side_map.get(d.b.file)
        if src is not None:
            return {"start": d.a.pos, "end": d.a.end,
                    "text": src[d.b.pos:d.b.end]}
    return None


def lift(base_rev: str, diffs: List[Diff], *, seed: str = "0",
         timestamp: str = EPOCH_ISO, sources=None) -> List[Op]:
    """Diff records → Op records.

    Op ids are deterministic: a function of the seed, the diff content,
    and the diff's position in the stream — the same inputs yield
    bit-identical op logs from any backend. With ``sources`` (a
    :func:`source_maps` pair), add/delete/changeSig ops carry
    structured-apply payloads in ``effects["decl"]``.
    """
    ops: List[Op] = []
    for idx, d in enumerate(diffs):
        prov = {"rev": base_rev, "timestamp": timestamp}
        payload = _decl_payload(d, sources)
        if d.kind == "rename" and d.a and d.b:
            ops.append(Op.new(
                "renameSymbol",
                Target(symbolId=d.a.symbolId, addressId=d.a.addressId),
                params={"oldName": d.a.name, "newName": d.b.name, "file": d.b.file},
                guards={"exists": True, "addressMatch": d.a.addressId},
                effects={"summary": f"rename {d.a.name}→{d.b.name}"},
                provenance=prov,
                op_id=_op_id(seed, base_rev, idx, "renameSymbol", d),
            ))
        elif d.kind == "move" and d.a and d.b:
            ops.append(Op.new(
                "moveDecl",
                Target(symbolId=d.a.symbolId, addressId=d.a.addressId),
                params={
                    "oldAddress": d.a.addressId,
                    "newAddress": d.b.addressId,
                    "oldFile": d.a.file,
                    "newFile": d.b.file,
                },
                guards={"exists": True, "addressMatch": d.a.addressId},
                effects={"summary": f"move {d.a.addressId}→{d.b.addressId}"},
                provenance=prov,
                op_id=_op_id(seed, base_rev, idx, "moveDecl", d),
            ))
        elif d.kind == "changeSig" and d.a and d.b:
            effects = {"summary":
                       f"changeSignature {d.a.name}: {d.a.signature}→{d.b.signature}"}
            if payload is not None:
                effects["decl"] = payload
            ops.append(Op.new(
                "changeSignature",
                Target(symbolId=d.a.symbolId, addressId=d.a.addressId),
                params={
                    "name": d.a.name,
                    "file": d.b.file,
                    "oldSignature": d.a.signature,
                    "newSignature": d.b.signature,
                    "oldAddress": d.a.addressId,
                    "newAddress": d.b.addressId,
                    "newSymbolId": d.b.symbolId,
                },
                guards={"exists": True, "addressMatch": d.a.addressId},
                effects=effects,
                provenance=prov,
                op_id=_op_id(seed, base_rev, idx, "changeSignature", d),
            ))
        elif d.kind == "add" and d.b:
            effects = {"summary": "add decl"}
            if payload is not None:
                effects["decl"] = payload
            ops.append(Op.new(
                "addDecl",
                Target(symbolId=d.b.symbolId, addressId=d.b.addressId),
                params={"file": d.b.file},
                guards={},
                effects=effects,
                provenance=prov,
                op_id=_op_id(seed, base_rev, idx, "addDecl", d),
            ))
        elif d.kind == "delete" and d.a:
            effects = {"summary": "delete decl"}
            if payload is not None:
                effects["decl"] = payload
            ops.append(Op.new(
                "deleteDecl",
                Target(symbolId=d.a.symbolId, addressId=d.a.addressId),
                params={"file": d.a.file},
                guards={},
                effects=effects,
                provenance=prov,
                op_id=_op_id(seed, base_rev, idx, "deleteDecl", d),
            ))
    return ops


def statement_edits(base_nodes: List[DeclNode], side_nodes: List[DeclNode],
                    sources, *, base_rev: str, seed: str,
                    timestamp: str = EPOCH_ISO, start_idx: int = 0) -> List[Op]:
    """``editStmtBlock`` ops: identity-stable decls whose body changed.

    The reference *schemas* statement-level edits (its requirements
    gate two [CFR-002] conflict categories on them, reference
    ``requirements.md:97-98``; design sketch at
    ``architecture.md:160``) but extracts none. This pass implements
    the capability: a declaration present in base and side under the
    same ``(symbolId, name, file)`` key whose full-span source text
    (``pos..end``, leading trivia included per the full-start span
    contract) differs emits one ``editStmtBlock`` op carrying old/new
    body text + 64-bit body hashes — enough for the applier to splice
    and for ``semrebase`` to replay. Matching by name+file (not
    addressId) tolerates the position shifts earlier edits in the same
    file cause; a decl that was renamed or moved AND body-edited stays
    outside this pass's reach (the rename/move op already records the
    change). Key collisions (same signature, name, file) keep the last
    occurrence, matching the differ's JS-``Map`` semantics.

    Op ids continue the lift stream's index sequence (``start_idx`` =
    number of lifted ops), so ids stay deterministic functions of
    (seed, rev, stream position, content). Opt-in: parity mode must
    keep the reference's observable op vocabulary, so this runs only
    under ``--statement-ops`` / ``[engine] statement_ops`` / strict
    conflict mode.
    """
    base_map, side_map = sources
    by_key: Dict[tuple, DeclNode] = {}
    for n in base_nodes:
        by_key[(n.symbolId, n.name, n.file)] = n  # last wins, Map quirk
    ops: List[Op] = []
    idx = start_idx
    prov = {"rev": base_rev, "timestamp": timestamp}
    for b in side_nodes:
        a = by_key.get((b.symbolId, b.name, b.file))
        if a is None:
            continue
        src_a = base_map.get(a.file)
        src_b = side_map.get(b.file)
        if src_a is None or src_b is None:
            continue
        old = src_a[a.pos:a.end]
        new = src_b[b.pos:b.end]
        if old == new:
            continue
        from .ids import stable_hash_hex
        ops.append(Op.new(
            "editStmtBlock",
            Target(symbolId=a.symbolId, addressId=a.addressId),
            params={"file": b.file,
                    "oldBodyHash": stable_hash_hex(old, n_hex=16),
                    "newBodyHash": stable_hash_hex(new, n_hex=16),
                    "oldBody": old, "newBody": new},
            guards={"exists": True, "addressMatch": a.addressId},
            effects={"summary": f"edit body of {a.name}"},
            provenance=prov,
            op_id=deterministic_op_id(seed, base_rev, idx, "editStmtBlock",
                                      a.symbolId, a.addressId, b.addressId),
        ))
        idx += 1
    return ops


def _decl_block(text: str) -> str:
    """The whitespace-normalized statement block of a declaration's
    source text: everything between the first ``{`` and the last ``}``,
    collapsed to single spaces. Empty when the decl has no braced body
    (``declare``/arrow-less vars) or the block is blank — callers skip
    those."""
    lo = text.find("{")
    hi = text.rfind("}")
    if lo < 0 or hi <= lo:
        return ""
    return " ".join(text[lo + 1:hi].split())


_IDENT_CHARS = frozenset(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789_$")

#: Minimum normalized-block size for motion evidence: at least this
#: many statements (``;`` count) or strictly more characters. A
#: trivial shared block — the bare ``return null;`` class — occurs in
#: unrelated declarations by coincidence, and since ``blockHash`` is
#: content-only, opposite-side trivial "motions" join into a false
#: ExtractVsInline strict-mode abort of a clean merge (ADVICE round 5).
_MIN_MOTION_STMTS = 2
_MIN_MOTION_CHARS = 15


def _block_significant(block: str) -> bool:
    """Whether a normalized block is big enough to be motion evidence."""
    return (len(block) > _MIN_MOTION_CHARS
            or block.count(";") >= _MIN_MOTION_STMTS)


def _block_in(block: str, text: str) -> bool:
    """True when ``block`` occurs in ``text`` at identifier boundaries:
    a raw substring check would let ``x + 1;`` "match" inside
    ``max + 1;`` and mint a motion for code that never moved. Both
    strings are already whitespace-normalized."""
    start = 0
    while True:
        i = text.find(block, start)
        if i < 0:
            return False
        before_ok = i == 0 or (text[i - 1] not in _IDENT_CHARS
                               or block[0] not in _IDENT_CHARS)
        j = i + len(block)
        after_ok = j >= len(text) or (text[j] not in _IDENT_CHARS
                                      or block[-1] not in _IDENT_CHARS)
        if before_ok and after_ok:
            return True
        start = i + 1


def body_motions(diffs, stmt_ops: List[Op], sources,
                 *, base_rev: str, seed: str,
                 timestamp: str = EPOCH_ISO, start_idx: int = 0) -> List[Op]:
    """``extractMethod`` / ``inlineMethod`` ops: statement-block motion
    between declarations.

    The reference names extract/inline in its op vocabulary and gates a
    [CFR-002] conflict category on them (reference
    ``requirements.md:52,98``) but its worker emits neither. This pass
    detects the motions from the already-lifted evidence:

    - **extract** — an added declaration N whose braced body appears
      (whitespace-normalized) in the OLD body of a body-edited
      declaration D but not in its NEW body: N's statements left D.
    - **inline** — a deleted declaration N whose body appears in a
      body-edited D's NEW body but not its OLD body: N's statements
      landed in D.

    Emitted ops are *markers*: the companion ``editStmtBlock`` /
    ``addDecl`` / ``deleteDecl`` ops still carry the text-level change
    (the applier skips unknown-to-it types by contract), so the markers
    add the semantic identity of the motion — the join key
    (``blockHash`` over the normalized block) the strict conflict
    detector and [RES-004] dedup need — without double-applying
    anything. One motion per added/deleted decl (first matching edit in
    stream order wins); ids continue the statement stream's index
    sequence, keeping the whole op stream a deterministic function of
    (seed, rev, content).

    Blocks below the minimum size (:func:`_block_significant`) are not
    motion evidence; and the edit bodies are pre-indexed by a cheap
    fingerprint — each body normalized exactly once, a length bucket
    (a block cannot occur in a body shorter than itself), and one
    NUL-joined haystack of every body so the common no-motion candidate
    is rejected by a single C-speed substring scan instead of
    per-edit boundary-aware scans (O(adds×edits×body) before)."""
    base_map, side_map = sources
    edits = [op for op in stmt_ops if op.type == "editStmtBlock"]
    ops: List[Op] = []
    idx = start_idx
    if not edits:
        return ops
    prov = {"rev": base_rev, "timestamp": timestamp}
    from .ids import stable_hash_hex
    norm_old = [" ".join(str(e.params.get("oldBody", "")).split())
                for e in edits]
    norm_new = [" ".join(str(e.params.get("newBody", "")).split())
                for e in edits]
    max_body = max(map(len, norm_old + norm_new))
    # '\x00' never survives whitespace normalization of source text, so
    # a block cannot falsely match across two bodies' boundary.
    haystack = "\x00".join(norm_old + norm_new)
    for d in diffs:
        if d.kind == "add" and d.b is not None:
            node, src = d.b, side_map.get(d.b.file)
        elif d.kind == "delete" and d.a is not None:
            node, src = d.a, base_map.get(d.a.file)
        else:
            continue
        if src is None:
            continue
        block = _decl_block(src[node.pos:node.end])
        if not block or not _block_significant(block):
            continue
        if len(block) > max_body or block not in haystack:
            continue  # no body contains the block — no scan needed
        for e, old, new in zip(edits, norm_old, norm_new):
            if d.kind == "add" and _block_in(block, old) \
                    and not _block_in(block, new):
                ops.append(Op.new(
                    "extractMethod",
                    Target(symbolId=e.target.symbolId,
                           addressId=e.target.addressId),
                    params={"file": node.file, "newName": node.name,
                            "newAddress": node.addressId,
                            "newSymbol": node.symbolId,
                            "fromFile": str(e.params.get("file", "")),
                            "blockHash": stable_hash_hex(block, n_hex=16)},
                    guards={"exists": True},
                    effects={"summary": f"extract {node.name}"},
                    provenance=prov,
                    op_id=deterministic_op_id(
                        seed, base_rev, idx, "extractMethod",
                        e.target.symbolId, node.addressId, block),
                ))
            elif d.kind == "delete" and _block_in(block, new) \
                    and not _block_in(block, old):
                ops.append(Op.new(
                    "inlineMethod",
                    Target(symbolId=e.target.symbolId,
                           addressId=e.target.addressId),
                    params={"file": str(e.params.get("file", "")),
                            "methodName": node.name,
                            "oldAddress": node.addressId,
                            "oldSymbol": node.symbolId,
                            "blockHash": stable_hash_hex(block, n_hex=16)},
                    guards={"exists": True},
                    effects={"summary": f"inline {node.name}"},
                    provenance=prov,
                    op_id=deterministic_op_id(
                        seed, base_rev, idx, "inlineMethod",
                        e.target.symbolId, node.addressId, block),
                ))
            else:
                continue
            idx += 1
            break
    return ops


def lift_statements(diffs, base_nodes, side_nodes, sources, files_pair,
                    *, base_rev: str, seed: str, side: str,
                    timestamp: str = EPOCH_ISO) -> List[Op]:
    """The statement-op tail of one side's lifted stream — the single
    place that owns the seed/side and start-index conventions every
    backend must share (op ids continue the lift sequence, so a
    convention drift would silently fork ids between backends).
    ``sources`` reuses an already-built :func:`source_maps` pair;
    ``files_pair`` builds one lazily otherwise."""
    sm = sources or source_maps(*files_pair)
    stmt = statement_edits(base_nodes, side_nodes, sm, base_rev=base_rev,
                           seed=f"{seed}/{side}", timestamp=timestamp,
                           start_idx=len(diffs))
    return stmt + body_motions(diffs, stmt, sm,
                               base_rev=base_rev, seed=f"{seed}/{side}",
                               timestamp=timestamp,
                               start_idx=len(diffs) + len(stmt))


def _op_id(seed: str, rev: str, idx: int, op_type: str, d: Diff) -> str:
    a_addr = d.a.addressId if d.a else ""
    b_addr = d.b.addressId if d.b else ""
    sym = (d.a or d.b).symbolId  # type: ignore[union-attr]
    return deterministic_op_id(seed, rev, idx, op_type, sym, a_addr, b_addr)
