"""Operation data contracts.

This is the parity surface with the reference engine: the wire/JSON shape
of an operation record must round-trip with the reference's op schema
(reference ``semmerge/ops.py:31-121`` and ``workers/ts/src/protocol.ts:4-13``):

    {"id", "schemaVersion", "type",
     "target": {"symbolId", "addressId"},
     "params", "guards", "effects", "provenance"}

Differences from the reference, by design:

- Serialization uses canonical compact JSON (stdlib ``json`` with
  ``separators=(",", ":")``), byte-compatible with the reference's
  ``orjson.dumps`` output for the same dict.
- ``Op.new`` takes an optional deterministic id. The reference mints
  ``uuid4()`` ids and wall-clock timestamps (reference
  ``workers/ts/src/lift.ts:5-9``), which violates its own determinism
  requirement (reference ``requirements.md:163`` [NFR-DET-001]); here the
  id scheme lives in :mod:`semantic_merge_tpu.core.ids` and is seeded.
- Precedence lives here as ``OP_PRECEDENCE`` (reference
  ``semmerge/compose.py:130-149``) because it is part of the op data
  model (it defines the canonical sort order), not of the composer.
"""
from __future__ import annotations

import json
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Literal, Mapping

OpType = Literal[
    "renameSymbol",
    "moveDecl",
    "addDecl",
    "deleteDecl",
    "changeSignature",
    "reorderParams",
    "addParam",
    "removeParam",
    "extractMethod",
    "inlineMethod",
    "updateCall",
    "editStmtBlock",
    "modifyImport",
    "reorderImports",
    "moveFile",
    "renameFile",
    "modifyNamespace",
]

#: The 17 operation kinds, in schema order (reference ``semmerge/ops.py:10-28``).
OP_TYPES: tuple[str, ...] = OpType.__args__  # type: ignore[attr-defined]

#: Composition precedence — lower composes earlier
#: (reference ``semmerge/compose.py:130-149``).
OP_PRECEDENCE: Dict[str, int] = {
    "moveDecl": 10,
    "renameSymbol": 11,
    "modifyImport": 12,
    "reorderImports": 13,
    "changeSignature": 20,
    "updateCall": 21,
    "addDecl": 30,
    "deleteDecl": 31,
    "extractMethod": 40,
    "inlineMethod": 41,
    "editStmtBlock": 50,
    "reorderParams": 51,
    "addParam": 52,
    "removeParam": 53,
    "moveFile": 60,
    "renameFile": 61,
    "modifyNamespace": 70,
}

#: Precedence assigned to unknown op types by the composer's sort
#: (reference ``semmerge/compose.py:18``).
UNKNOWN_PRECEDENCE = 99


def dumps_canonical(obj: Any) -> str:
    """Compact JSON, byte-compatible with the reference's orjson output."""
    return json.dumps(obj, separators=(",", ":"), ensure_ascii=False)


_JSON_SCALARS = (str, int, float, bool, type(None))


def copy_json(value: Any) -> Any:
    """Deep copy of a JSON-shaped value (dict/list/scalars). Scalars are
    immutable and returned as-is; anything exotic falls back to
    :func:`copy.deepcopy`. Exact ``type`` checks keep the hot scalar
    path to one tuple-membership test — this runs 4×/op in the
    composers' materialize step."""
    t = type(value)
    if t in _JSON_SCALARS:
        return value
    if t is dict:
        return {k: copy_json(v) for k, v in value.items()}
    if t is list:
        return [copy_json(v) for v in value]
    if isinstance(value, _JSON_SCALARS):  # scalar subclasses
        return value
    import copy
    return copy.deepcopy(value)


@dataclass(slots=True)
class Target:
    """The declaration an op acts on (reference ``semmerge/ops.py:31-39``)."""

    symbolId: str
    addressId: str | None = None

    def to_dict(self) -> Dict[str, Any]:
        return {"symbolId": self.symbolId, "addressId": self.addressId}


@dataclass(slots=True)
class Op:
    """One semantic change record (reference ``semmerge/ops.py:42-103``).

    ``slots=True``: a 10k-file merge materializes ~90k of these straight
    off the device fetch — slotted construction measured ~25% cheaper,
    and materialize is the largest host phase of the fused path."""

    id: str
    schemaVersion: int
    type: str
    target: Target
    params: Dict[str, Any]
    guards: Dict[str, Any]
    effects: Dict[str, Any]
    provenance: Dict[str, Any]

    def clone(self) -> "Op":
        """Independent copy safe to mutate (the composer's materialize
        step rewrites params/target in place). Equivalent to the
        reference's deep clone (reference ``semmerge/compose.py:117-127``)
        but specialized for the JSON-shaped payloads ops actually carry —
        ~6× cheaper than :func:`copy.deepcopy`, which dominated the
        composed-op decode at the 1k-file benchmark rung."""
        return Op(
            id=self.id, schemaVersion=self.schemaVersion, type=self.type,
            target=Target(symbolId=self.target.symbolId,
                          addressId=self.target.addressId),
            params=copy_json(self.params), guards=copy_json(self.guards),
            effects=copy_json(self.effects),
            provenance=copy_json(self.provenance),
        )

    @staticmethod
    def new(
        op_type: str,
        target: Target,
        params: Dict[str, Any] | None = None,
        guards: Dict[str, Any] | None = None,
        effects: Dict[str, Any] | None = None,
        provenance: Dict[str, Any] | None = None,
        op_id: str | None = None,
    ) -> "Op":
        return Op(
            id=op_id if op_id is not None else str(uuid.uuid4()),
            schemaVersion=1,
            type=op_type,
            target=target,
            params=params or {},
            guards=guards or {},
            effects=effects or {},
            provenance=provenance or {},
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "schemaVersion": self.schemaVersion,
            "type": self.type,
            "target": self.target.to_dict(),
            "params": self.params,
            "guards": self.guards,
            "effects": self.effects,
            "provenance": self.provenance,
        }

    @staticmethod
    def from_dict(data: Mapping[str, Any]) -> "Op":
        return Op(
            id=str(data["id"]),
            schemaVersion=int(data.get("schemaVersion", 1)),
            type=data["type"],
            target=Target(**data["target"]),
            params=dict(data.get("params", {})),
            guards=dict(data.get("guards", {})),
            effects=dict(data.get("effects", {})),
            provenance=dict(data.get("provenance", {})),
        )

    def pretty(self) -> str:
        return f"{self.type} {self.target.symbolId} {self.params}"

    def sort_key(self) -> tuple[int, str, str]:
        """The canonical composition sort key
        (reference ``semmerge/compose.py:16-18``)."""
        timestamp = str(self.provenance.get("timestamp", "1970-01-01T00:00:00Z"))
        return (OP_PRECEDENCE.get(self.type, UNKNOWN_PRECEDENCE), timestamp, self.id)


@dataclass
class OpLog:
    """An ordered collection of ops (reference ``semmerge/ops.py:106-121``)."""

    ops: List[Op] = field(default_factory=list)

    def to_json(self) -> str:
        # Columnar op-log views (ops/oplog_view.py) serialize straight
        # from their device columns — byte-identical output, no Op
        # materialization (the notes payload is the hot consumer).
        fast = getattr(self.ops, "to_json", None)
        if fast is not None:
            return fast()
        return dumps_canonical([o.to_dict() for o in self.ops])

    def to_json_bytes(self) -> bytes:
        """UTF-8 bytes of :meth:`to_json`; columnar views hand the
        native serializer's buffer through without a decode/encode
        round trip."""
        fast = getattr(self.ops, "to_json_bytes", None)
        if fast is not None:
            return fast()
        return self.to_json().encode("utf-8")

    @staticmethod
    def from_json(data: str) -> "OpLog":
        return OpLog([Op.from_dict(item) for item in json.loads(data)])

    def extend(self, ops: Iterable[Op]) -> None:
        self.ops.extend(ops)

    def __len__(self) -> int:
        return len(self.ops)

    def __iter__(self):
        return iter(self.ops)
