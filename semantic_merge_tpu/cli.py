"""Command-line orchestrator.

The reference CLI surface (reference ``semmerge/__main__.py:28-88``)
with the same observable contract:

- ``semdiff REV1 REV2 [--json-out]`` — print the op log between two
  revisions (pretty lines or JSON).
- ``semmerge BASE A B [--inplace] [--git]`` — full 3-way semantic merge.
  Exit codes: 0 merged; 1 conflicts (written to
  ``.semmerge-conflicts.json``); 2 type errors (diagnostics on stderr);
  3 git plumbing failure; 10-17 a contained fault under
  ``SEMMERGE_STRICT=1`` / ``--no-degrade`` (or, for 17, under
  ``--resolve require``; see ``errors.py`` and the runbook's "Failure
  modes" table).

Conflict resolution — when compose yields conflicts and ``--resolve``
/ ``SEMMERGE_RESOLVE`` is ``auto`` or ``require``, the resolution tier
(:mod:`semantic_merge_tpu.resolve`) proposes per-category candidates
and accepts only proposals that pass every verify gate; anything else
falls back to conflict-as-result, byte-identical to the tier being
off. Strict mode forces the tier off.

Additions over the reference: ``--backend`` / ``--trace`` / ``--seed``
flags, config actually loaded (backend + seed + formatter resolved from
``.semmerge.toml``), deterministic provenance (commit timestamps), and
``semrebase`` replay of a stored op log onto a new base.

Fault containment — the **degradation ladder**: any
:class:`~semantic_merge_tpu.errors.MergeFault` escaping a merge rung
degrades the run to the next rung instead of crashing the driver:

    fused/TPU (or subprocess) backend  →  host backend  →
    whole-tree textual 3-way merge (``runtime/textmerge.py``)

Each transition is recorded as a ``degradation`` span and a
``merge_degradations_total{from,to,fault}`` counter. ``SEMMERGE_STRICT=1``
or ``--no-degrade`` fails fast with the fault's documented exit code.
The textual rung is the LastMerge/DeepMerge floor: never worse than
git's own 3-way text merge.
"""
from __future__ import annotations

import argparse
import json
import os
import pathlib
import shutil
import subprocess
import sys
import time
from typing import Iterable, List, Sequence

from .backends.base import get_backend
from .config import load_config
from .core.compose import compose_oplogs
from .core.ops import OpLog
from .errors import MergeFault, fault_boundary
from .runtime.applier import apply_ops
from .runtime.emitter import emit_files
from .runtime.git import commit_timestamp_iso, resolve_rev, snapshot_rev
from .runtime.notes import notes_get, notes_put
from .runtime.trace import Tracer
from .runtime.verify import typecheck_ts
from .utils.loggingx import logger

CONFLICTS_ARTIFACT = ".semmerge-conflicts.json"


def _conflicts_path() -> pathlib.Path:
    """The conflicts artifact lands in the request's repo root when a
    merge service request is in scope (utils/workdir), cwd otherwise."""
    from .utils import workdir
    return workdir.root() / CONFLICTS_ARTIFACT


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="semmerge", description="TPU-native semantic merge engine")
    sub = parser.add_subparsers(dest="command", required=True)

    p_diff = sub.add_parser("semdiff", help="Semantic diff: print op log between two revisions")
    p_diff.add_argument("rev1")
    p_diff.add_argument("rev2")
    p_diff.add_argument("--json-out", action="store_true", help="Emit JSON instead of a pretty listing")
    p_diff.add_argument("--backend", default=None, help="Language backend (host|tpu)")
    p_diff.add_argument("--trace", action="store_true", help="Write .semmerge-trace.json")
    p_diff.add_argument("--profile", metavar="DIR", default=None,
                        help="Capture a JAX profiler trace into DIR "
                             "(phases annotated for TensorBoard/XProf)")
    p_diff.add_argument("--change-signature", action="store_true",
                        help="Detect changeSignature ops instead of delete+add "
                             "(also [engine].change_signature in .semmerge.toml)")
    p_diff.add_argument("--signature-matcher", action="store_true",
                        help="Pair renamed+retyped decls by embedding "
                             "similarity (also [engine].signature_matcher)")
    p_diff.add_argument("--statement-ops", action="store_true",
                        help="Extract editStmtBlock ops for body-only decl "
                             "edits (also [engine].statement_ops)")

    p_merge = sub.add_parser("semmerge", help="Semantic merge base A B into working tree")
    p_merge.add_argument("base", nargs="?", default=None)
    p_merge.add_argument("a", nargs="?", default=None)
    p_merge.add_argument("b", nargs="?", default=None)
    p_merge.add_argument("--inplace", action="store_true",
                         help="Write the merge result into the current working tree "
                              "(crash-safe: staged, journaled, atomically committed)")
    p_merge.add_argument("--no-degrade", action="store_true",
                         help="Fail fast with the fault's documented exit code "
                              "instead of walking the degradation ladder "
                              "(same as SEMMERGE_STRICT=1)")
    p_merge.add_argument("--resolve", nargs="?", const="auto", default=None,
                         choices=("off", "auto", "require"),
                         help="Conflict-resolution tier posture (also "
                              "SEMMERGE_RESOLVE). auto: resolve when every "
                              "verify gate passes, fall back to conflict-as-"
                              "result otherwise; require: a resolver fault "
                              "exits 17 instead of falling back. Always off "
                              "under --no-degrade/SEMMERGE_STRICT=1")
    p_merge.add_argument("--resume", action="store_true",
                         help="Complete (or roll back) an interrupted --inplace "
                              "commit in the current directory, then exit")
    p_merge.add_argument("--git", action="store_true",
                         help="Flag set when invoked via git merge driver")
    p_merge.add_argument("--backend", default=None, help="Language backend (host|tpu)")
    p_merge.add_argument("--trace", action="store_true", help="Write .semmerge-trace.json")
    p_merge.add_argument("--profile", metavar="DIR", default=None,
                         help="Capture a JAX profiler trace into DIR "
                              "(phases annotated for TensorBoard/XProf)")
    p_merge.add_argument("--seed", default=None, help="Deterministic id seed override")
    p_merge.add_argument("--change-signature", action="store_true",
                         help="Detect changeSignature ops instead of delete+add "
                              "(also [engine].change_signature in .semmerge.toml)")
    p_merge.add_argument("--signature-matcher", action="store_true",
                         help="Pair renamed+retyped decls by embedding "
                              "similarity (also [engine].signature_matcher)")
    p_merge.add_argument("--strict-conflicts", action="store_true",
                         help="Detect all [CFR-002] conflict categories via a "
                              "full symbol join (also [engine].conflict_mode)")
    p_merge.add_argument("--structured-apply", action="store_true",
                         help="Ops carry decl text/spans so add/delete/"
                              "changeSignature materialize structurally "
                              "(also [engine].structured_apply)")
    p_merge.add_argument("--statement-ops", action="store_true",
                         help="Extract editStmtBlock ops for body-only decl "
                              "edits; implied by --strict-conflicts "
                              "(also [engine].statement_ops)")

    p_rebase = sub.add_parser("semrebase", help="Replay a commit's stored op log onto a revision")
    p_rebase.add_argument("commit", help="Commit whose semmerge note holds the op log")
    p_rebase.add_argument("onto", help="Revision to replay onto")
    p_rebase.add_argument("--inplace", action="store_true")

    p_serve = sub.add_parser("serve",
                             help="Run the warm-state merge service daemon "
                                  "on a unix socket (see runbook: Service "
                                  "mode)")
    p_serve.add_argument("--socket", default=None,
                         help="Unix socket path or tcp://host:port "
                              "(tcp://host:0 picks an ephemeral port; "
                              "mTLS via SEMMERGE_FLEET_TLS_*). Default: "
                              "SEMMERGE_SERVICE_SOCKET, else "
                              "$XDG_RUNTIME_DIR/semmerge.sock, else "
                              "/tmp/semmerge-<uid>.sock)")
    p_serve.add_argument("--join", default=None, metavar="ROUTER",
                         help="Announce this daemon to a fleet router "
                              "(unix path or tcp://host:port) and keep "
                              "re-announcing every "
                              "SEMMERGE_FLEET_JOIN_INTERVAL seconds — "
                              "elastic membership instead of a "
                              "router-spawned subprocess")
    p_serve.add_argument("--advertise", default=None, metavar="ADDR",
                         help="Address the router should dial this "
                              "member on (default: the bound --socket; "
                              "set it when NAT/bind-all makes the bound "
                              "address undialable)")
    p_serve.add_argument("--capacity", type=int, default=None,
                         help="Relative capacity announced in the join "
                              "handshake (default 1)")
    p_serve.add_argument("--member-id", default=None,
                         help="Stable member id to join as (default: "
                              "router-assigned r1, r2, …)")
    p_serve.add_argument("--workers", type=int, default=None,
                         help="Executor threads (SEMMERGE_SERVICE_WORKERS, "
                              "default 4)")
    p_serve.add_argument("--queue", type=int, default=None,
                         help="Admission queue bound (SEMMERGE_SERVICE_QUEUE,"
                              " default 16); a full queue rejects with a "
                              "typed WorkerFault, exit 12")
    p_serve.add_argument("--idle-exit", type=float, default=None,
                         help="Exit after this many idle seconds "
                              "(SEMMERGE_SERVICE_IDLE_EXIT, default 900; "
                              "0 disables)")
    p_serve.add_argument("--events", default=None,
                         help="Write the daemon's span/event stream to this "
                              "JSONL path on exit")
    p_serve.add_argument("--status", action="store_true",
                         help="Query a running daemon's status and exit "
                              "(does not start one)")
    p_serve.add_argument("--fleet", action="store_true",
                         help="With --status against a fleet router: "
                              "include every member's status block, "
                              "aggregated through the router")
    p_serve.add_argument("--supervise", action="store_true",
                         help="Run under a supervisor that respawns a "
                              "crashed daemon with capped backoff "
                              "(SEMMERGE_SUPERVISE_BACKOFF[_CAP], "
                              "SEMMERGE_SUPERVISE_MAX_RESTARTS); a clean "
                              "exit (idle-exit, shutdown) ends supervision")

    p_fleet = sub.add_parser(
        "fleet",
        help="Run a fault-tolerant routing tier over N supervised merge "
             "daemons: consistent-hash repo affinity, health-aware "
             "failover, a durable dispatch WAL, and hedged reads (see "
             "runbook: Fleet mode)")
    p_fleet.add_argument("--socket", default=None,
                         help="Client-facing unix socket or "
                              "tcp://host:port (same resolution chain "
                              "as serve; mTLS via SEMMERGE_FLEET_TLS_*);"
                              " local members bind <socket>.m0, .m1, …")
    p_fleet.add_argument("--members", type=int, default=None,
                         help="Local member daemons to supervise "
                              "(SEMMERGE_FLEET_MEMBERS, default 3; 0 = "
                              "pure-remote fleet serving only members "
                              "that `semmerge serve --join` in)")
    p_fleet.add_argument("--workers", type=int, default=None,
                         help="Executor threads per member "
                              "(SEMMERGE_SERVICE_WORKERS, default 4)")
    p_fleet.add_argument("--queue", type=int, default=None,
                         help="Admission queue bound per member")
    p_fleet.add_argument("--wal-dir", default=None,
                         help="Dispatch WAL directory "
                              "(SEMMERGE_FLEET_WAL_DIR, default "
                              "<socket>.semmerge-fleet-wal/)")
    p_fleet.add_argument("--status", action="store_true",
                         help="Query a running router's status and exit")
    p_fleet.add_argument("--drain", default=None, metavar="MEMBER",
                         help="Drain one member (e.g. m1) out of a running "
                              "fleet and exit; 'all' drains the router "
                              "itself")
    p_fleet.add_argument("--leave", default=None, metavar="MEMBER",
                         help="Remove a joined remote member (by id or "
                              "advertised address) from a running fleet "
                              "and exit — the deliberate-departure path: "
                              "its keys hand off, no failover is counted")

    p_stats = sub.add_parser("stats",
                             help="Pretty-print a semmerge trace/metrics "
                                  "artifact (.semmerge-trace.json, "
                                  ".semmerge-events.jsonl, or a "
                                  "SEMMERGE_METRICS dump)")
    p_stats.add_argument("artifact", nargs="?", default=".semmerge-trace.json",
                         help="Artifact path (default .semmerge-trace.json)")
    p_stats.add_argument("--json", action="store_true",
                         help="Emit the artifact back as JSON instead of "
                              "the pretty rendering")
    p_stats.add_argument("--prometheus", action="store_true",
                         help="Render the artifact's metrics as Prometheus "
                              "text exposition")
    p_stats.add_argument("--daemon", action="store_true",
                         help="Query the live merge service daemon instead "
                              "of reading an artifact file")
    p_stats.add_argument("--fleet", action="store_true",
                         help="With --daemon against a fleet router: "
                              "aggregate every member's status through the "
                              "router (no per-member socket addresses)")

    p_trace = sub.add_parser("trace",
                             help="Trace-artifact tooling (see runbook: "
                                  "Observability)")
    trace_sub = p_trace.add_subparsers(dest="trace_command", required=True)
    p_analyze = trace_sub.add_parser(
        "analyze",
        help="Per-request critical-path latency attribution: queue wait / "
             "batch window / pack / kernel / host tail / apply, from a "
             ".semmerge-trace.json or postmortem bundle (p50/p99 over a "
             "directory of them)")
    p_analyze.add_argument("artifact",
                           help="Trace or postmortem artifact, or a "
                                "directory of them")
    p_analyze.add_argument("--json", action="store_true",
                           help="Emit the breakdown as JSON")
    p_analyze.add_argument("--fleet", action="store_true",
                           help="Router-hop attribution for stitched fleet "
                                "traces (SEMMERGE_FLEET_TRACE_DIR "
                                "artifacts): route / wal_fsync / relay / "
                                "hedge_wait / member_execute")
    p_analyze.add_argument("--since", default=None, metavar="DURATION",
                           help="Directory mode: only artifacts modified "
                                "within DURATION (e.g. 90s, 15m, 2h, 1d) "
                                "— rotated trace dirs mix epochs")
    p_tdiff = trace_sub.add_parser(
        "diff",
        help="Phase-aligned diff of two trace artifacts (A = offender, "
             "B = baseline): per-phase ms delta/ratio, top contributor "
             "named suspect_phase — manual latency attribution, same "
             "shape the anomaly auto-triage bundles embed")
    p_tdiff.add_argument("a", help="Offender artifact (trace, fleet "
                                   "trace, or triage/postmortem bundle)")
    p_tdiff.add_argument("b", help="Baseline artifact")
    p_tdiff.add_argument("--json", action="store_true",
                         help="Emit the diff as JSON")

    p_top = sub.add_parser(
        "top",
        help="Live one-screen fleet dashboard: QPS, windowed p50/p99, "
             "queue depth, breaker states, residency hit rate, mesh "
             "occupancy, member health — polled from the daemon/router "
             "status + federated metrics (keys: q quit, p pause)")
    p_top.add_argument("--socket", default=None,
                       help="Daemon or fleet-router socket (default: the "
                            "serve socket resolution chain)")
    p_top.add_argument("--interval", type=float, default=2.0,
                       help="Poll interval seconds (default 2.0)")
    p_top.add_argument("--once", action="store_true",
                       help="Print a single frame and exit (scripts/CI; "
                            "also the non-TTY behavior)")
    p_top.add_argument("--json", action="store_true",
                       help="With --once: emit the frame's source data "
                            "as JSON instead of the rendering")

    p_profile = sub.add_parser(
        "profile",
        help="Capture a bounded JAX profiler trace + metrics delta from "
             "a RUNNING merge service daemon (see runbook: Performance "
             "objectives & profiling)")
    p_profile.add_argument("--daemon", action="store_true", required=True,
                           help="Required: captures come from the live "
                                "daemon (one-shot runs use --profile DIR "
                                "on the merge verbs instead)")
    p_profile.add_argument("--seconds", type=float, default=1.0,
                           help="Capture window length (clamped to "
                                "[0.1, 60]; default 1.0)")
    p_profile.add_argument("--out", default=None,
                           help="Bundle parent directory (default: "
                                "SEMMERGE_PROFILE_DIR, else a "
                                "semmerge-profiles dir under the system "
                                "temp dir)")
    p_profile.add_argument("--socket", default=None,
                           help="Daemon socket path (default: the serve "
                                "socket resolution chain)")
    p_profile.add_argument("--json", action="store_true",
                           help="Emit the capture result as JSON")

    p_perf = sub.add_parser(
        "perf",
        help="Perf-regression sentinel: record bench snapshots into "
             "PERF_BASELINE.json and compare new runs against it")
    perf_sub = p_perf.add_subparsers(dest="perf_command", required=True)
    p_rec = perf_sub.add_parser(
        "record", help="Normalize bench JSON snapshots (or a live "
                       "daemon latency window) into the baseline")
    p_rec.add_argument("snapshots", nargs="*",
                       help="BENCH_*.json files to record (key = file "
                            "stem minus the BENCH_ prefix)")
    p_rec.add_argument("--baseline", default=None,
                       help="Baseline path (default ./PERF_BASELINE.json)")
    p_rec.add_argument("--key", default=None,
                       help="Override the baseline key (single snapshot "
                            "or --daemon only)")
    p_rec.add_argument("--daemon", action="store_true",
                       help="Record the live daemon's request-latency "
                            "window instead of files (key 'daemon')")
    p_rec.add_argument("--socket", default=None)
    p_cmp = perf_sub.add_parser(
        "compare", help="Compare snapshots against the baseline; exit 1 "
                        "on regression")
    p_cmp.add_argument("snapshots", nargs="*",
                       help="BENCH_*.json files to compare")
    p_cmp.add_argument("--baseline", default=None,
                       help="Baseline path (default ./PERF_BASELINE.json)")
    p_cmp.add_argument("--tolerance-pct", type=float, default=None,
                       help="Headline-value tolerance (default 10)")
    p_cmp.add_argument("--phase-tolerance-pct", type=float, default=None,
                       help="Per-phase wall tolerance (default 25)")
    p_cmp.add_argument("--daemon", action="store_true",
                       help="Compare the live daemon's latency window "
                            "against its recorded baseline entry")
    p_cmp.add_argument("--socket", default=None)
    p_cmp.add_argument("--json", action="store_true",
                       help="Emit findings as JSON")

    p_train = sub.add_parser("train-matcher",
                             help="Train the decl-similarity matcher (orbax "
                                  "checkpoints; resumes from the latest)")
    p_train.add_argument("--steps", type=int, default=200)
    p_train.add_argument("--batch", type=int, default=32)
    p_train.add_argument("--seq", type=int, default=64)
    p_train.add_argument("--seed", type=int, default=0)
    p_train.add_argument("--ckpt-dir", default=None)
    p_train.add_argument("--ckpt-every", type=int, default=50)
    p_train.add_argument("--no-resume", action="store_true")
    p_train.add_argument("--eval", action="store_true",
                         help="After training, report held-out pairing "
                              "precision/recall (models.evaluate)")
    return parser


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command in ("semdiff", "semmerge", "semrebase"):
        # Explicit allowlist of one-shot merge-shaped commands: anything
        # long-running or embedded (train-matcher today, future servers)
        # must keep normal collection cadence (see utils/gctune).
        from .utils.gctune import tune_for_merge
        tune_for_merge()
        # Persistent compile cache for driver-shaped cold starts (the
        # reference's cold ≤40 s budget frame); jaxenv.force_cpu drops
        # it again on CPU-pinned runs (XLA:CPU AOT reload of collective
        # executables aborts — see utils/jaxenv).
        from .utils.jaxenv import enable_compile_cache
        enable_compile_cache()
    try:
        if args.command == "semdiff":
            return cmd_semdiff(args)
        if args.command == "semmerge":
            return cmd_semmerge(args)
        if args.command == "semrebase":
            return cmd_semrebase(args)
        if args.command == "train-matcher":
            return cmd_train_matcher(args)
        if args.command == "stats":
            return cmd_stats(args)
        if args.command == "trace":
            return cmd_trace(args)
        if args.command == "top":
            return cmd_top(args)
        if args.command == "profile":
            return cmd_profile(args)
        if args.command == "perf":
            return cmd_perf(args)
        if args.command == "serve":
            return cmd_serve(args)
        if args.command == "fleet":
            return cmd_fleet(args)
    except subprocess.CalledProcessError as exc:
        cmd = exc.cmd if isinstance(exc.cmd, str) else " ".join(map(str, exc.cmd))
        print(f"error: subprocess failed ({cmd}): exit {exc.returncode}", file=sys.stderr)
        return 3
    except MergeFault as fault:
        # A contained fault escaping outside the semmerge ladder
        # (semdiff, semrebase) still exits with its documented code
        # instead of a raw traceback.
        return _fail_fast(fault)
    return 2


def _resolve_backend(name_flag: str | None):
    config = load_config()
    from .frontend.declcache import configure as configure_cache
    configure_cache(config.core.memory_cap_mb)
    name = name_flag or config.engine.backend
    if name in ("tpu", "ts_tpu"):
        # No-op single-host; on pods every process joins the global
        # mesh before any device code runs.
        from .parallel.distributed import init_distributed
        try:
            init_distributed()
        except Exception as exc:
            logger.warning("distributed bring-up failed (%s); continuing single-host", exc)
    try:
        backend = get_backend(name)
    except Exception as exc:  # TPU backend unavailable → host fallback
        if name != "host":
            logger.warning("Backend %r unavailable (%s); falling back to host", name, exc)
            return get_backend("host"), config
        raise
    # Additional enabled languages route through a composite backend:
    # one run semantically merges every enabled language.
    from .backends.multi import route_backends
    try:
        multi = route_backends(backend, config)
    except Exception as exc:
        logger.warning("language routing failed (%s); single backend", exc)
        multi = None
    if multi is not None:
        backend = multi
    configure = getattr(backend, "configure", None)
    if configure is not None:
        configure(config)
    return backend, config


def _signature_matcher(args, config, change_sig):
    """Build the embedding matcher when enabled (CLI flag or config)."""
    if not change_sig:
        return None
    if not (getattr(args, "signature_matcher", False)
            or config.engine.signature_matcher):
        return None
    from .models.signature import EmbeddingSignatureMatcher
    return EmbeddingSignatureMatcher(
        threshold=config.engine.signature_threshold,
        ckpt_dir=config.engine.matcher_ckpt_dir)


def cmd_semdiff(args: argparse.Namespace) -> int:
    tracer = Tracer(enabled=args.trace, profile_dir=args.profile)
    backend, config = _resolve_backend(args.backend)
    change_sig = args.change_signature or config.engine.change_signature
    stmt_ops = (getattr(args, "statement_ops", False)
                or config.engine.statement_ops)
    try:
        with tracer.phase("snapshot"):
            from .runtime.git import (archive_bytes, collision_safe_scope,
                                      diff_scope, snapshot_from_bytes)
            scope = (diff_scope(args.rev1, args.rev2)
                     if config.engine.incremental else None)
            rev1_tar = archive_bytes(args.rev1)
            rev2_tar = archive_bytes(args.rev2)
            base_snap = snapshot_from_bytes(rev1_tar, paths=scope)
            right_snap = snapshot_from_bytes(rev2_tar, paths=scope)
            if scope is not None and collision_safe_scope(
                    scope, rev1_tar, resolve_rev(args.rev1),
                    (base_snap, right_snap)) is None:
                logger.info("incremental scope disabled: a scoped "
                            "symbolId has an out-of-scope twin")
                scope = None
                base_snap = snapshot_from_bytes(rev1_tar)
                right_snap = snapshot_from_bytes(rev2_tar)
            from .service import residency
            if residency.residency_enabled():
                from .frontend.snapshot import annotate_residency
                from .runtime.git import tree_oid
                from .utils import workdir
                annotate_residency(base_snap, str(workdir.current()),
                                   tree_oid(args.rev1), scope=scope)
        with tracer.phase("diff"):
            ops = backend.diff(base_snap, right_snap,
                               base_rev=resolve_rev(args.rev1),
                               timestamp=commit_timestamp_iso(args.rev2),
                               change_signature=change_sig,
                               signature_matcher=_signature_matcher(
                                   args, config, change_sig),
                               statement_ops=stmt_ops)
    finally:
        backend.close()
        tracer.close()
    if args.json_out:
        print(json.dumps([op.to_dict() for op in ops], indent=2))
    else:
        for op in ops:
            print(op.pretty())
    tracer.write()
    return 0


def _strict_mode(args: argparse.Namespace) -> bool:
    """Fail-fast mode: ``--no-degrade`` or ``SEMMERGE_STRICT=1`` (read
    through the request overlay so daemon requests carry their client's
    posture)."""
    from .utils import reqenv
    return (getattr(args, "no_degrade", False)
            or (reqenv.get("SEMMERGE_STRICT", "") or "").strip() == "1")


def _fail_fast(fault: MergeFault) -> int:
    from .obs import flight as obs_flight
    from .obs import metrics as obs_metrics
    from .obs import spans as obs_spans
    from .service.resilience import breakers
    from .utils import workdir
    obs_metrics.REGISTRY.counter(
        "merge_faults_total",
        "Merge runs failed on a contained fault, by fault and stage",
    ).inc(1, fault=type(fault).__name__, stage=fault.stage)
    # The fault escapes the ladder: leave a postmortem bundle (flight
    # ring + fault chain + breaker states) next to the repo, keyed by
    # the trace id the client sees in its error line.
    tid = obs_spans.trace_id() or obs_flight.default_trace_id()
    obs_flight.dump(tid, "fault-escape", fault=fault,
                    breakers=breakers().snapshot(), root=workdir.root())
    print(f"semmerge: {fault.describe()} (exit {fault.exit_code}) "
          f"[trace {tid}]", file=sys.stderr)
    return fault.exit_code


def _record_degradation(frm: str, to: str, fault: MergeFault,
                        tracer: Tracer) -> None:
    """One ladder rung transition: log + metric + span + trace counter."""
    from .obs import metrics as obs_metrics
    from .obs import spans as obs_spans
    name = type(fault).__name__
    logger.warning("merge degrading %s -> %s after %s",
                   frm, to, fault.describe())
    obs_metrics.REGISTRY.counter(
        "merge_degradations_total",
        "Degradation-ladder rung transitions, by fault",
    ).inc(1, **{"from": frm, "to": to, "fault": name})
    obs_spans.record("degradation", 0.0, layer="cli",
                     **{"from": frm, "to": to, "fault": name,
                        "stage": fault.stage})
    from .obs import flight as obs_flight
    from .service.resilience import breakers
    from .utils import workdir
    obs_flight.dump(obs_spans.trace_id(), "degradation", fault=fault,
                    breakers=breakers().snapshot(), root=workdir.root(),
                    extra={"degradation": {"from": frm, "to": to}})
    tracer.count("degradations", tracer.counters.get("degradations", 0) + 1)


def cmd_semmerge(args: argparse.Namespace) -> int:
    if getattr(args, "resume", False):
        from .runtime.inplace import recover, repo_lock
        with repo_lock():
            action, n_writes = recover()
        detail = f" ({n_writes} writes)" if action == "rolled-forward" else ""
        print(f"inplace recovery: {action}{detail}")
        return 0
    if not (args.base and args.a and args.b):
        print("error: semmerge requires BASE A B revisions (or --resume)",
              file=sys.stderr)
        return 2
    logger.info("Starting semantic merge base=%s A=%s B=%s", args.base, args.a, args.b)
    if args.inplace:
        # A journal/stage left by an interrupted --inplace commit is
        # resolved before this merge touches anything; recovery mutates
        # the work tree, so it holds the same repo lock as the commit.
        from .runtime.inplace import recover, repo_lock
        with repo_lock():
            recover()
    tracer = Tracer(enabled=args.trace, profile_dir=args.profile)
    try:
        return _merge_ladder(args, tracer, strict=_strict_mode(args))
    finally:
        tracer.write()
        from .frontend.declcache import publish_metrics
        publish_metrics()


def _breaker_open_fault(rung: str) -> MergeFault:
    from .errors import WorkerFault
    return WorkerFault(f"circuit breaker open for rung {rung!r}: "
                       f"skipping without an attempt", stage="breaker",
                       cause="breaker-open")


def _merge_ladder(args: argparse.Namespace, tracer: Tracer,
                  *, strict: bool) -> int:
    """Walk the degradation ladder: resolved backend → host backend →
    whole-tree textual 3-way merge. Conflicts (exit 1) and type errors
    (exit 2) are merge *results* and never degrade; only
    :class:`MergeFault` moves the run down a rung.

    Each rung consults its circuit breaker (service/resilience.py): a
    rung whose breaker is open is skipped *without* paying the failed
    attempt — the skip is recorded as a normal degradation with
    ``cause="breaker-open"``. Rung outcomes feed the breaker: merge
    results (0/1/2) count as rung success; a :class:`MergeFault` counts
    as failure. The board is a no-op outside the daemon unless
    ``SEMMERGE_BREAKER=on``."""
    from .service.resilience import breakers
    board = breakers()
    backend, config = _resolve_backend(args.backend)
    rung_name = getattr(backend, "name", "?")
    host_like = rung_name in ("host", "ts_host")
    if not board.allow(rung_name):
        backend.close()
        fault = _breaker_open_fault(rung_name)
        if strict:
            return _fail_fast(fault)
        _record_degradation(rung_name, "text" if host_like else "host",
                            fault, tracer)
    else:
        try:
            try:
                code = _semantic_attempt(args, config, backend, tracer)
            finally:
                backend.close()
            board.record_success(rung_name)
            return code
        except MergeFault as fault:
            board.record_failure(rung_name)
            if strict:
                return _fail_fast(fault)
            _record_degradation(rung_name, "text" if host_like else "host",
                                fault, tracer)
    if not host_like:
        if not board.allow("host"):
            _record_degradation("host", "text", _breaker_open_fault("host"),
                                tracer)
        else:
            try:
                with fault_boundary("merge"):
                    host_backend, host_config = _resolve_backend("host")
                try:
                    code = _semantic_attempt(args, host_config, host_backend,
                                             tracer)
                finally:
                    host_backend.close()
                board.record_success("host")
                return code
            except MergeFault as fault:
                board.record_failure("host")
                _record_degradation("host", "text", fault, tracer)
    try:
        return _textual_rung(args, tracer)
    except MergeFault as fault:
        # The floor itself failed: nothing left to degrade to.
        return _fail_fast(fault)


def _semantic_attempt(args: argparse.Namespace, config, backend,
                      tracer: Tracer) -> int:
    """One semantic-merge rung. Returns the merge's exit code (0/1/2);
    raises :class:`MergeFault` when a pipeline stage fails — each CLI
    phase runs inside a :class:`fault_boundary` that classifies
    unexpected exceptions into the stage's typed fault."""
    merged_tree: pathlib.Path | None = None
    try:
        with tracer.phase("snapshot"), fault_boundary("snapshot"):
            from .runtime.git import (archive_bytes, collision_safe_scope,
                                      merge_scope, snapshot_from_bytes)
            base_tar = archive_bytes(args.base)
            left_tar = archive_bytes(args.a)
            right_tar = archive_bytes(args.b)
            # Incremental scope: scan/diff only files either side
            # touched; the full tars still feed apply + text fallback,
            # so non-indexed and unchanged files keep exact semantics.
            scope = (merge_scope(args.base, args.a, args.b)
                     if config.engine.incremental else None)
            base_snap = snapshot_from_bytes(base_tar, paths=scope)
            left_snap = snapshot_from_bytes(left_tar, paths=scope)
            right_snap = snapshot_from_bytes(right_tar, paths=scope)
            if scope is not None and collision_safe_scope(
                    scope, base_tar, resolve_rev(args.base),
                    (base_snap, left_snap, right_snap)) is None:
                # A scoped symbolId has an out-of-scope twin: under
                # Map-last-wins the restriction could change which
                # occurrence survives — fall back to the full scan
                # (see runtime/git.py merge_scope).
                logger.info("incremental scope disabled: a scoped "
                            "symbolId has an out-of-scope twin")
                scope = None
                base_snap = snapshot_from_bytes(base_tar)
                left_snap = snapshot_from_bytes(left_tar)
                right_snap = snapshot_from_bytes(right_tar)
            if scope is not None:
                tracer.count("scope_files", len(scope))
            # The base tree repeats across merges of one repo (every
            # feature branch merges against the same main) — key it
            # into the warm residency cache so a daemon serving repeat
            # requests skips scan+encode+h2d for it. Enabled-check
            # first: one-shot runs skip the extra rev-parse.
            from .service import residency
            if residency.residency_enabled():
                from .frontend.snapshot import annotate_residency
                from .runtime.git import tree_oid
                from .utils import workdir
                annotate_residency(base_snap, str(workdir.current()),
                                   tree_oid(args.base), scope=scope)
        base_rev = resolve_rev(args.base)
        seed = args.seed or config.core.deterministic_seed
        if seed == "auto":
            seed = base_rev
        timestamp = commit_timestamp_iso(args.base)

        change_sig = args.change_signature or config.engine.change_signature
        structured = (getattr(args, "structured_apply", False)
                      or config.engine.structured_apply)
        strict = (getattr(args, "strict_conflicts", False)
                  or config.engine.conflict_mode == "strict")
        # Strict mode implies statement ops: the ConcurrentStmtEdit
        # category has no inputs without editStmtBlock extraction.
        stmt_ops = (getattr(args, "statement_ops", False)
                    or config.engine.statement_ops or strict)
        sig_matcher = _signature_matcher(args, config, change_sig)
        if not strict:
            # The normal path goes through the backend's fused merge
            # entry point — on the TPU backend that is one device
            # round trip for diff + op identity + composition.
            from .backends.base import run_merge
            with tracer.phase("merge", backend=backend.name), \
                    fault_boundary("merge"):
                result, composed, conflicts = run_merge(
                    backend, base_snap, left_snap, right_snap,
                    base_rev=base_rev, seed=seed, timestamp=timestamp,
                    change_signature=change_sig, structured_apply=structured,
                    signature_matcher=sig_matcher, statement_ops=stmt_ops)
        else:
            # Strict conflict detection inspects the raw op logs between
            # diff and compose, so it needs the two-step path.
            with tracer.phase("build_and_diff", backend=backend.name), \
                    fault_boundary("merge"):
                result = backend.build_and_diff(
                    base_snap, left_snap, right_snap,
                    base_rev=base_rev, seed=seed, timestamp=timestamp,
                    change_signature=change_sig, structured_apply=structured,
                    signature_matcher=sig_matcher, statement_ops=stmt_ops)
            with tracer.phase("compose"), fault_boundary("merge"):
                from .core.strict_conflicts import detect_conflicts_strict
                from .obs import spans as obs_spans
                with obs_spans.span("strict_detect", layer="core",
                                    n_a=len(result.op_log_left),
                                    n_b=len(result.op_log_right)):
                    ops_left, ops_right, conflicts = detect_conflicts_strict(
                        result.op_log_left, result.op_log_right)
                compose_fn = getattr(backend, "compose", None) or compose_oplogs
                composed, walk_conflicts = compose_fn(ops_left, ops_right)
                conflicts.extend(walk_conflicts)
        tracer.count("ops_left", len(result.op_log_left))
        tracer.count("ops_right", len(result.op_log_right))
        from .frontend.declcache import global_cache
        cache = global_cache()
        if cache is not None:  # cache hit rate (reference architecture.md:248)
            tracer.count("decl_cache_hits", cache.hits)
            tracer.count("decl_cache_misses", cache.misses)
        tracer.count("composed_ops", len(composed))
        tracer.count("conflicts", len(conflicts))

        resolutions = None
        if conflicts:
            from .resolve import posture as resolve_posture
            # Strict mode forces the tier off: fail-fast runs must not
            # synthesize output, whatever the posture says.
            posture = "off" if _strict_mode(args) else resolve_posture(args)
            resolved = False
            if posture != "off":
                from .resolve import engine as resolve_engine
                outcome = None
                try:
                    with tracer.phase("resolve"), fault_boundary("resolve"):
                        outcome = resolve_engine.resolve_conflicts(
                            conflicts, list(result.op_log_left),
                            list(result.op_log_right), composed=composed,
                            base_tar=base_tar, left_tar=left_tar,
                            right_tar=right_tar, strict_detect=strict,
                            config=config)
                except MergeFault as fault:
                    if posture == "require":
                        # Tier availability IS the require contract: the
                        # conflicts are still computed results, so the
                        # artifact is written before the fault exit.
                        _write_conflict_reports(conflicts)
                        return _fail_fast(fault)
                    resolve_engine.record_resolver_fault(fault)
                if outcome is not None:
                    resolutions = outcome.records
                    if outcome.accepted:
                        composed = outcome.composed
                        resolved = True
            # The artifact always carries the audit trail when the tier
            # ran — rejected proposals on the conflict exit, accepted
            # ones next to the success exit (the merged tree's evidence).
            _write_conflict_reports(conflicts, resolutions)
            if not resolved:
                return 1
        else:
            # A clean merge must not leave a stale artifact from a
            # previous conflicted run next to a success exit code.
            _conflicts_path().unlink(missing_ok=True)

        with tracer.phase("materialize"), fault_boundary("apply"):
            from .runtime.git import temp_tree
            with temp_tree(base_tar) as base_tree:
                # tpu backend: the merge's reorderImports RGA lists
                # materialize as one batched device program.
                merged_tree = apply_ops(
                    base_tree, composed,
                    device_crdt=getattr(backend, "device_crdt", False))
            deleted_paths: list = []
            text_written: list = []
            if config.engine.text_fallback:
                # [FBK-001]: files outside the active backend's indexed
                # set merge textually.
                from .runtime.textmerge import apply_text_fallback
                text_conflicts, deleted_paths, text_written = \
                    apply_text_fallback(
                        merged_tree, base_tar, left_tar, right_tar,
                        indexed_extensions=getattr(backend, "extensions",
                                                   None))
                tracer.count("text_conflicts", len(text_conflicts))
                if text_conflicts:
                    _write_conflict_reports(text_conflicts, resolutions)
                    return 1
        with tracer.phase("format"), fault_boundary("format"):
            formatter = None
            ts_cfg = config.languages.get("typescript")
            if ts_cfg and ts_cfg.formatter_cmd:
                formatter = list(ts_cfg.formatter_cmd)
            touched = None
            if config.engine.formatter_scope == "touched":
                # Everything the merge wrote: the op stream's path
                # params plus text-fallback writes of FORMATTER-parseable
                # suffixes. The filter must be the formatter's language
                # set, not the backend's indexed extensions — text
                # fallback only ever writes files OUTSIDE the indexed
                # set, so the two are disjoint by construction and the
                # old filter dropped every text-merged .json/.md/.css
                # while letting notes.txt through when no backend set
                # existed. A text-merged notes.txt or binary must not
                # reach prettier as an explicit arg. Untouched files
                # keep their bytes. Columnar composed views answer
                # straight from their columns (no Op materialization).
                from .runtime.applier import _normalize_relpath, touched_paths
                from .runtime.emitter import PRETTIER_EXTENSIONS
                touched = touched_paths(composed)
                touched.update(
                    str(_normalize_relpath(p)) for p in text_written
                    if pathlib.PurePosixPath(p).suffix.lower()
                    in PRETTIER_EXTENSIONS)
            emit_files(merged_tree, formatter, paths=touched)
        with tracer.phase("typecheck"), fault_boundary("verify"):
            if config.ci.require_typecheck:
                ok, diagnostics = typecheck_ts(merged_tree)
            else:
                ok, diagnostics = True, []
        if not ok:
            for line in diagnostics:
                print(line, file=sys.stderr)
            return 2

        if args.inplace:
            # Crash-safe publish: stage → journal → atomic renames,
            # under the repo-level lock so concurrent --inplace runs
            # (one-shot or daemon) exclude each other. Text-merge
            # deletions propagate through the same journal.
            with fault_boundary("commit"):
                from .runtime.inplace import commit_tree_inplace, repo_lock
                with repo_lock():
                    commit_tree_inplace(merged_tree, deletes=deleted_paths)

        with tracer.phase("notes"):
            notes_put(resolve_rev(args.a), OpLog(result.op_log_left))
            notes_put(resolve_rev(args.b), OpLog(result.op_log_right))
        logger.info("Merge complete")
        return 0
    finally:
        if merged_tree is not None:
            _cleanup([merged_tree])


def _textual_rung(args: argparse.Namespace, tracer: Tracer) -> int:
    """The ladder's floor: a whole-tree textual 3-way merge — every
    file resolves through :func:`runtime.textmerge.apply_text_fallback`
    with an EMPTY indexed set, i.e. git-equivalent 3-way semantics for
    the entire tree. No semantic engine, no formatter, no typecheck:
    the guarantee is "never worse than ``git merge``", byte-for-byte."""
    from .runtime.git import archive_bytes, temp_tree
    from .runtime.textmerge import apply_text_fallback
    with tracer.phase("text_merge"), fault_boundary("apply"):
        base_tar = archive_bytes(args.base)
        left_tar = archive_bytes(args.a)
        right_tar = archive_bytes(args.b)
        with temp_tree(base_tar) as merged_tree:
            conflicts, deleted_paths, _written = apply_text_fallback(
                merged_tree, base_tar, left_tar, right_tar,
                indexed_extensions=frozenset())
            tracer.count("text_conflicts", len(conflicts))
            if conflicts:
                _write_conflict_reports(conflicts)
                return 1
            _conflicts_path().unlink(missing_ok=True)
            if args.inplace:
                with fault_boundary("commit"):
                    from .runtime.inplace import (commit_tree_inplace,
                                                  repo_lock)
                    with repo_lock():
                        commit_tree_inplace(merged_tree,
                                            deletes=deleted_paths)
    logger.info("Merge complete (textual fallback)")
    return 0


def cmd_semrebase(args: argparse.Namespace) -> int:
    """Replay the op log stored on *commit* onto *onto* — the [SPEC]
    ``semrebase`` flow (reference ``requirements.md:119-124``), made real
    by the readable notes store."""
    oplog = notes_get(resolve_rev(args.commit))
    if oplog is None:
        print(f"No semmerge op log stored for {args.commit}", file=sys.stderr)
        return 1
    from .runtime.git import checkout_tree_to_temp
    base_tree = checkout_tree_to_temp(args.onto)
    try:
        merged = apply_ops(base_tree, list(oplog))
        emit_files(merged)
        if args.inplace:
            # Same crash-safe two-phase commit as semmerge --inplace.
            from .runtime.inplace import commit_tree_inplace, repo_lock
            with repo_lock():
                commit_tree_inplace(merged)
            _cleanup([merged])
        else:
            print(str(merged))
    finally:
        _cleanup([base_tree])
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    """Start (or, with ``--status``, query) the merge service daemon."""
    from .service import client as service_client
    if args.status:
        method = "member_status" if getattr(args, "fleet", False) \
            else "status"
        try:
            status = service_client.call_control(method, path=args.socket)
        except service_client.DaemonUnavailable as exc:
            print(f"semmerge serve: no daemon running ({exc})",
                  file=sys.stderr)
            return 1
        print(json.dumps(status, indent=2, default=str))
        return 0
    if getattr(args, "supervise", False):
        # The supervisor process stays import-light (no jax, no engine):
        # nothing in it can fail the way the daemon child does.
        from .service.supervisor import Supervisor, serve_argv
        return Supervisor(serve_argv(args)).run()
    from .service.daemon import Daemon
    daemon = Daemon(socket_path=args.socket, workers=args.workers,
                    queue_size=args.queue, idle_exit=args.idle_exit,
                    events_path=args.events,
                    join=getattr(args, "join", None),
                    advertise=getattr(args, "advertise", None),
                    capacity=getattr(args, "capacity", None),
                    member_id=getattr(args, "member_id", None))
    return daemon.serve_forever()


def cmd_fleet(args: argparse.Namespace) -> int:
    """Start (or query/drain) the fleet router. The router process is
    import-light like the supervisor — members carry the heavy
    runtime."""
    from .service import client as service_client
    if args.status:
        try:
            status = service_client.call_control("status",
                                                 path=args.socket)
        except service_client.DaemonUnavailable as exc:
            print(f"semmerge fleet: no router running ({exc})",
                  file=sys.stderr)
            return 1
        print(json.dumps(status, indent=2, default=str))
        return 0 if status.get("fleet") else 1
    if args.drain:
        params = {} if args.drain == "all" else {"member": args.drain}
        try:
            result = service_client.call_control("drain", params=params,
                                                 path=args.socket)
        except service_client.DaemonUnavailable as exc:
            print(f"semmerge fleet: no router running ({exc})",
                  file=sys.stderr)
            return 1
        print(json.dumps(result, indent=2, default=str))
        return 0 if result.get("ok") else 1
    if getattr(args, "leave", None):
        try:
            result = service_client.call_control(
                "leave", params={"member": args.leave}, path=args.socket)
        except service_client.DaemonUnavailable as exc:
            print(f"semmerge fleet: no router running ({exc})",
                  file=sys.stderr)
            return 1
        print(json.dumps(result, indent=2, default=str))
        return 0 if result.get("ok") else 1
    from .fleet.router import FleetRouter
    router = FleetRouter(socket_path=args.socket, members=args.members,
                         workers=args.workers, queue_size=args.queue,
                         wal_dir=args.wal_dir)
    return router.serve_forever()


def cmd_profile(args: argparse.Namespace) -> int:
    """On-demand profile capture from the live daemon (the ``profile``
    wire verb). The daemon holds a single-capture lock; a concurrent
    capture answers ``ok=False`` without disturbing the running one."""
    from .service import client as service_client
    try:
        result = service_client.capture_profile(
            args.seconds, out_dir=args.out, path=args.socket)
    except service_client.DaemonUnavailable as exc:
        print(f"error: no merge service daemon reachable ({exc})",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(result, indent=2, default=str))
        return 0 if result.get("ok") else 1
    if not result.get("ok"):
        print(f"error: profile capture failed: "
              f"{result.get('error', 'unknown')}", file=sys.stderr)
        return 1
    print(f"profile bundle: {result.get('dir')}")
    print(f"  window: {result.get('seconds', 0.0):g}s  "
          f"profiler_started={result.get('profiler_started')}")
    for name in result.get("files", ()):
        print(f"  {name}")
    return 0


def cmd_perf(args: argparse.Namespace) -> int:
    """Perf-regression sentinel (`perf record|compare`): thin CLI over
    :mod:`semantic_merge_tpu.obs.perf`; `scripts/perf_gate.py` is the
    standalone CI face of the same core."""
    from .obs import perf as obs_perf
    from .utils import workdir
    baseline_path = pathlib.Path(args.baseline) if args.baseline \
        else workdir.root() / obs_perf.BASELINE_NAME

    def _daemon_entry() -> dict | None:
        from .service import client as service_client
        try:
            status = service_client.call_control("status",
                                                 path=args.socket)
        except service_client.DaemonUnavailable as exc:
            print(f"error: no merge service daemon reachable ({exc})",
                  file=sys.stderr)
            return None
        return obs_perf.daemon_entry(status)

    def _load_entries() -> dict | None:
        entries: dict = {}
        if args.daemon:
            entry = _daemon_entry()
            if entry is None:
                return None
            entries[getattr(args, "key", None) or "daemon"] = entry
        for raw in args.snapshots:
            path = pathlib.Path(raw)
            try:
                record = json.loads(path.read_text(encoding="utf-8"))
            except (OSError, ValueError) as exc:
                print(f"error: cannot read snapshot {path}: {exc}",
                      file=sys.stderr)
                return None
            key = args.key if getattr(args, "key", None) \
                and len(args.snapshots) == 1 and not args.daemon \
                else obs_perf.record_key(path)
            entries[key] = obs_perf.normalize_record(record,
                                                     source=str(path))
        if not entries:
            print("error: nothing to process (pass snapshot files or "
                  "--daemon)", file=sys.stderr)
            return None
        return entries

    if args.perf_command == "record":
        entries = _load_entries()
        if entries is None:
            return 2
        existing: dict = {}
        if baseline_path.is_file():
            try:
                existing = obs_perf.load_baseline(baseline_path)["entries"]
            except (OSError, ValueError) as exc:
                print(f"error: unreadable baseline {baseline_path}: {exc}",
                      file=sys.stderr)
                return 2
        existing.update(entries)
        obs_perf.save_baseline(baseline_path, existing)
        print(f"recorded {len(entries)} entr"
              f"{'y' if len(entries) == 1 else 'ies'} into "
              f"{baseline_path}: {', '.join(sorted(entries))}")
        return 0

    # compare
    if not baseline_path.is_file():
        print(f"error: no baseline at {baseline_path} (record one with "
              f"'semmerge perf record')", file=sys.stderr)
        return 2
    try:
        baseline = obs_perf.load_baseline(baseline_path)
    except (OSError, ValueError) as exc:
        print(f"error: unreadable baseline {baseline_path}: {exc}",
              file=sys.stderr)
        return 2
    entries = _load_entries()
    if entries is None:
        return 2
    tol = args.tolerance_pct if args.tolerance_pct is not None \
        else obs_perf.DEFAULT_TOLERANCE_PCT
    ptol = args.phase_tolerance_pct \
        if args.phase_tolerance_pct is not None \
        else obs_perf.DEFAULT_PHASE_TOLERANCE_PCT
    ok, findings = obs_perf.compare_many(
        entries, baseline, tolerance_pct=tol, phase_tolerance_pct=ptol)
    if getattr(args, "json", False):
        print(json.dumps({"ok": ok, "findings": findings}, indent=2))
    else:
        print(f"perf compare vs {baseline_path}: "
              f"{'OK' if ok else 'REGRESSION'}")
        print(obs_perf.format_findings(findings))
    return 0 if ok else 1


def _stats_fleet(args: argparse.Namespace, service_client) -> int:
    """``semmerge stats --daemon --fleet``: one router round-trip
    (``member_status`` / federated ``metrics``) instead of N per-member
    socket addresses."""
    if getattr(args, "prometheus", False):
        try:
            result = service_client.call_control("metrics")
        except service_client.DaemonUnavailable as exc:
            print(f"error: no fleet router reachable ({exc})",
                  file=sys.stderr)
            return 1
        print(result.get("prometheus", ""), end="")
        return 0
    try:
        agg = service_client.call_control("member_status")
    except service_client.DaemonUnavailable as exc:
        print(f"error: no fleet router reachable ({exc})", file=sys.stderr)
        return 1
    if not isinstance(agg, dict) or "router" not in agg:
        print("error: peer is not a fleet router (plain daemon? drop "
              "--fleet)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(agg, indent=2, default=str))
        return 0
    router = agg.get("router") or {}
    members = agg.get("members") or {}
    up = router.get("members_up", 0)
    print(f"fleet pid={router.get('pid')} "
          f"uptime={router.get('uptime_s', 0.0):.1f}s "
          f"socket={router.get('socket')} "
          f"members_up={up}/{len(members)}")
    wal = router.get("wal") or {}
    print(f"requests: served={router.get('served_total', 0)} "
          f"in_flight={router.get('in_flight', 0)} "
          f"wal_open={wal.get('open', 0)} "
          f"wal_replayed={wal.get('replayed', 0)}")
    slo = router.get("slo")
    if slo:
        print(f"slo: {'healthy' if slo.get('healthy') else 'BURNING'}")
        for row in slo.get("objectives", ()):
            mark = "TRIPPED" if row.get("tripped") else "ok"
            print(f"  {mark:8s} {row.get('objective')}: "
                  f"burn fast={row.get('burn_fast', 0.0):.2f}x "
                  f"slow={row.get('burn_slow', 0.0):.2f}x")
    for member_id in sorted(members):
        st = members[member_id]
        if not isinstance(st, dict):
            print(f"member {member_id}: unreachable")
            continue
        state = st.get("state")
        if not st.get("ok"):
            # draining is a deliberate departure; dead is a failure —
            # the rollup keeps them distinct.
            print(f"member {member_id}: {state or 'unreachable'}")
            continue
        decl_rate = st.get("declcache_hit_rate", 0.0) or 0.0
        res_rate = (st.get("residency") or {}).get("hit_rate", 0.0) or 0.0
        print(f"member {member_id}: "
              f"{state or 'ready'} pid={st.get('pid')} "
              f"served={st.get('served_total', 0)} "
              f"queue_depth={st.get('queue_depth', 0)} "
              f"in_flight={st.get('in_flight', 0)} "
              f"rss_mb={st.get('rss_mb', 0.0):.1f} "
              f"declcache_hit_rate={decl_rate:.3f} "
              f"residency_hit_rate={res_rate:.3f}")
    return 0


def cmd_stats(args: argparse.Namespace) -> int:
    """Pretty-print an observability artifact: a ``.semmerge-trace.json``
    trace, a ``.semmerge-events.jsonl`` span/event stream, or a metrics
    registry dump (``SEMMERGE_METRICS=path``). Rendering reads only the
    file — it works on artifacts from long-gone processes. With
    ``--daemon`` the data comes from the live merge service instead."""
    if getattr(args, "daemon", False):
        from .service import client as service_client
        if getattr(args, "fleet", False):
            return _stats_fleet(args, service_client)
        try:
            status = service_client.call_control("status")
        except service_client.DaemonUnavailable as exc:
            print(f"error: no merge service daemon reachable ({exc})",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(status, indent=2, default=str))
            return 0
        if args.prometheus:
            from .obs.metrics import render_prometheus_from_dict
            print(render_prometheus_from_dict(status.get("metrics", {})),
                  end="")
            return 0
        decl = status.get("declcache") or {}
        print(f"daemon pid={status.get('pid')} "
              f"uptime={status.get('uptime_s', 0.0):.1f}s "
              f"socket={status.get('socket')}")
        print(f"requests: served={status.get('served_total', 0)} "
              f"queue_depth={status.get('queue_depth', 0)} "
              f"in_flight={status.get('in_flight', 0)} "
              f"workers={status.get('workers', 0)}")
        print(f"declcache: hit_rate={status.get('declcache_hit_rate', 0.0):.3f} "
              f"hits={decl.get('hits', 0)} misses={decl.get('misses', 0)} "
              f"evictions={decl.get('evictions', 0)} "
              f"entries={decl.get('entries', 0)}")
        res = status.get("residency")
        if res:
            ev = res.get("evictions") or {}
            print(f"residency: {'on' if res.get('enabled') else 'off'} "
                  f"hit_rate={res.get('hit_rate', 0.0):.3f} "
                  f"entries={res.get('entries', 0)} "
                  f"bytes={res.get('bytes', 0)}/"
                  f"{res.get('budget_bytes', 0)} "
                  f"evictions={sum(ev.values())}"
                  + ("".join(f" {k}={v}" for k, v in sorted(ev.items()))
                     if ev else ""))
        print(f"memory: rss_mb={status.get('rss_mb', 0.0):.1f} "
              f"repos_tracked={status.get('repos_tracked', 0)}")
        port = status.get("metrics_port")
        if port is not None:
            print(f"telemetry: http://127.0.0.1:{port} "
                  f"(/metrics, /healthz)")
        slo = status.get("slo")
        if slo:
            print(f"slo: {'healthy' if slo.get('healthy') else 'BURNING'} "
                  f"(fast {slo.get('windows', {}).get('fast_s', 0):g}s / "
                  f"slow {slo.get('windows', {}).get('slow_s', 0):g}s)")
            for row in slo.get("objectives", ()):
                mark = "TRIPPED" if row.get("tripped") else "ok"
                print(f"  {mark:8s} {row.get('objective')}: "
                      f"burn fast={row.get('burn_fast', 0.0):.2f}x "
                      f"slow={row.get('burn_slow', 0.0):.2f}x "
                      f"(n={row.get('samples_fast', 0)})")
            for verb, q in (slo.get("window_quantiles") or {}).items():
                print(f"  window {verb}: p50={q.get('p50_ms', 0.0):.1f}ms "
                      f"p99={q.get('p99_ms', 0.0):.1f}ms "
                      f"n={q.get('count', 0)} errors={q.get('errors', 0)}")
        batch = status.get("batch")
        if batch:
            cache = batch.get("program_cache") or {}
            print(f"batch: queue_depth={batch.get('queue_depth', 0)} "
                  f"batches={batch.get('batches_total', 0)} "
                  f"mean_batch_size={batch.get('mean_batch_size', 0.0):.2f} "
                  f"padding_waste={batch.get('padding_waste_ratio', 0.0):.3f} "
                  f"program_cache_hit_rate={cache.get('hit_rate', 0.0):.3f}")
        for line in _render_stats({"counters": status.get("metrics", {}).get(
                "counters", {})}):
            print(line)
        return 0
    path = pathlib.Path(args.artifact)
    if not path.is_file():
        print(f"error: no artifact at {path} (run `semmerge ... --trace` "
              f"or set SEMMERGE_METRICS=path first)", file=sys.stderr)
        return 1
    text = path.read_text(encoding="utf-8")
    if path.suffix == ".jsonl":
        rows = [json.loads(line) for line in text.splitlines() if line.strip()]
        data = {"events_jsonl": rows}
    else:
        data = json.loads(text)
    if args.json:
        print(json.dumps(data, indent=2))
        return 0
    if args.prometheus:
        from .obs.metrics import render_prometheus_from_dict
        metrics = data.get("metrics") if "metrics" in data else data
        if not isinstance(metrics, dict) or not any(
                k in metrics for k in ("counters", "gauges", "histograms")):
            print("error: artifact carries no metrics section", file=sys.stderr)
            return 1
        print(render_prometheus_from_dict(metrics), end="")
        return 0
    try:
        for line in _render_stats(data):
            print(line)
    except BrokenPipeError:  # stats | head is a normal way to read it
        try:
            sys.stdout.close()
        except OSError:
            pass
    return 0


def _render_stats(data: dict) -> List[str]:
    out: List[str] = []

    def _spans_table(rows) -> None:
        agg: dict = {}
        for r in rows:
            key = (r.get("layer") or "-", r["name"])
            n, total = agg.get(key, (0, 0.0))
            agg[key] = (n + 1, total + float(r.get("seconds", 0.0)))
        out.append(f"{'layer':<10} {'span':<24} {'count':>5} {'total ms':>10}")
        for (layer, name), (n, total) in sorted(
                agg.items(), key=lambda kv: -kv[1][1]):
            out.append(f"{layer:<10} {name:<24} {n:>5} {total * 1e3:>10.1f}")

    if "events_jsonl" in data:  # .semmerge-events.jsonl
        rows = data["events_jsonl"]
        spans = [r for r in rows if r.get("type") == "span"]
        events = [r for r in rows if r.get("type") == "event"]
        out.append(f"events stream: {len(spans)} spans, {len(events)} events")
        _spans_table(spans)
        for e in events:
            out.append(f"event {e.get('name')} @{e.get('t_start')}s "
                       f"{e.get('fields', {})}")
        return out

    if "phases" in data:  # .semmerge-trace.json
        out.append(f"trace (schema {data.get('schema', 0)}): "
                   f"total {data.get('total_seconds', 0.0) * 1e3:.1f} ms")
        out.append(f"{'phase':<24} {'ms':>10}  meta")
        for p in data["phases"]:
            out.append(f"{p['name']:<24} {p['seconds'] * 1e3:>10.1f}  "
                       f"{p.get('meta', '')}")
        counters = data.get("counters", {})
        if counters:
            out.append("counters: " + "  ".join(
                f"{k}={v}" for k, v in sorted(counters.items())))
        spans = data.get("spans")
        if spans:
            out.append(f"spans ({len(spans)}):")
            _spans_table(spans)
        device = data.get("device")
        if isinstance(device, dict):
            out.append("device: " + "  ".join(
                f"{k}={device[k]}" for k in sorted(device)
                if not isinstance(device[k], (dict, list))))
            for k in ("transfer_bytes", "transfer_count",
                      "compile_cache_events"):
                if device.get(k):
                    out.append(f"device.{k}: " + "  ".join(
                        f"{kk}={vv}" for kk, vv in sorted(device[k].items())))
        return out

    if any(k in data for k in ("counters", "gauges", "histograms")):
        # SEMMERGE_METRICS registry dump.
        for kind in ("counters", "gauges"):
            for name, m in sorted(data.get(kind, {}).items()):
                for s in m.get("series", []):
                    labels = ",".join(f"{k}={v}" for k, v in
                                      sorted(s.get("labels", {}).items()))
                    out.append(f"{name}{{{labels}}} {s['value']}")
        for name, m in sorted(data.get("histograms", {}).items()):
            for s in m.get("series", []):
                labels = ",".join(f"{k}={v}" for k, v in
                                  sorted(s.get("labels", {}).items()))
                out.append(f"{name}{{{labels}}} count={s['count']} "
                           f"sum={s['sum']:.6f}")
        return out

    out.append("unrecognized artifact shape; try --json")
    return out


#: Critical-path buckets of ``semmerge trace analyze`` — where one
#: request's wall time went, in pipeline order.
CRITICAL_PATH_BUCKETS = ("queue_wait", "batch_window", "pack", "kernel",
                         "host_tail", "apply")

#: Router-hop buckets of ``semmerge trace analyze --fleet`` — where one
#: routed request's wall time went across the fleet, in hop order.
FLEET_PATH_BUCKETS = ("route", "wal_fsync", "relay", "hedge_wait",
                      "member_execute")


def _bucket_span(name: str, layer) -> str | None:
    """Map one span to its critical-path bucket (None = unattributed).
    Nested double counting is avoided by bucketing only the leaf phase
    splits (fused-engine records, batch spans, the CLI apply phase),
    never the wrapper spans that contain them."""
    if name == "service.queue_wait":
        return "queue_wait"
    if name == "batch.window":
        return "batch_window"
    if name == "batch.pack":
        return "pack"
    if name in ("kernel", "batch.dispatch", "h2d"):
        return "kernel"
    if name in ("fetch", "compose_decode", "chain_decode",
                "materialize_overlap", "batch.scatter") or \
            (name == "materialize" and layer != "cli"):
        return "host_tail"
    if name == "materialize" and layer == "cli":
        return "apply"
    return None


def _load_span_artifact(path: pathlib.Path) -> tuple[dict | None, int]:
    """Load one span-shaped artifact: ``(data, corrupt_lines)``.

    ``.jsonl`` artifacts (daemon ``--events`` streams, rotated span
    logs) are salvaged line by line — a truncated tail or a corrupt row
    skips that row and counts it instead of sinking the whole file, so
    ``trace analyze`` keeps working on exactly the artifacts written
    while something was going wrong."""
    if path.suffix == ".jsonl":
        try:
            text = path.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError):
            return None, 0
        rows, bad = [], 0
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(row, dict):
                rows.append(row)
        if not rows:
            return None, bad
        return {"spans": rows, "trace_id": None}, bad
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None, 0
    return data, 0


def _parse_duration(raw: str) -> float:
    """``90s`` / ``15m`` / ``2h`` / ``1d`` (bare numbers = seconds) →
    seconds. Raises ValueError on nonsense."""
    text = str(raw).strip().lower()
    scale = 1.0
    if text and text[-1] in "smhd":
        scale = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}[text[-1]]
        text = text[:-1]
    value = float(text)
    if value < 0:
        raise ValueError(f"negative duration {raw!r}")
    return value * scale


def _analyze_artifact(path: pathlib.Path) -> dict | None:
    """One artifact's critical-path breakdown, or None when the file is
    not span-shaped (trace artifact or postmortem bundle)."""
    data, corrupt = _load_span_artifact(path)
    if not isinstance(data, dict) or not isinstance(data.get("spans"), list):
        return None
    buckets = {b: 0.0 for b in CRITICAL_PATH_BUCKETS}
    cli_total = 0.0
    for row in data["spans"]:
        if not isinstance(row, dict):
            continue
        name = row.get("name") or ""
        layer = row.get("layer")
        try:
            secs = float(row.get("seconds") or 0.0)
        except (TypeError, ValueError):
            continue
        if layer == "cli":
            cli_total += secs
        b = _bucket_span(name, layer)
        if b is not None:
            buckets[b] += secs
    # Wall estimate: the CLI phases cover the merge itself; queue wait
    # and the batch window happen before/around them. Engine-level
    # buckets (pack/kernel/host_tail) nest INSIDE the CLI merge phase,
    # so they attribute rather than extend the total.
    total = cli_total + buckets["queue_wait"] + buckets["batch_window"]
    accounted = sum(buckets.values())
    result = {
        "artifact": str(path),
        "trace_id": data.get("trace_id"),
        "reason": data.get("reason"),
        "total_seconds": round(total, 6),
        "buckets": {b: round(v, 6) for b, v in buckets.items()},
        "other_seconds": round(max(total - accounted, 0.0), 6),
    }
    if corrupt:
        result["corrupt_lines"] = corrupt
    return result


def _analyze_fleet_artifact(path: pathlib.Path) -> dict | None:
    """One *stitched* fleet-trace artifact's router-hop breakdown, or
    None when the file is not span-shaped. Buckets are non-overlapping:
    member execute time is carved out of the relay legs that carried
    it, relay out of the route spans that contain them — so the shares
    attribute rather than double count."""
    data, corrupt = _load_span_artifact(path)
    if not isinstance(data, dict) or not isinstance(data.get("spans"), list):
        return None
    wal = hedge_wait = relay_ok = route_like = 0.0
    member_exec = member_queue = 0.0
    for row in data["spans"]:
        if not isinstance(row, dict):
            continue
        name = row.get("name") or ""
        meta = row.get("meta") if isinstance(row.get("meta"), dict) else {}
        try:
            secs = float(row.get("seconds") or 0.0)
        except (TypeError, ValueError):
            continue
        if name == "fleet.wal_fsync":
            wal += secs
        elif name == "fleet.hedge_wait":
            hedge_wait += secs
        elif name == "fleet.relay" and meta.get("outcome") == "ok":
            relay_ok += secs
        elif name in ("fleet.route", "fleet.failover"):
            route_like += secs
        elif name == "service.execute" and "member" in meta:
            member_exec += secs
        elif name == "service.queue_wait" and "member" in meta:
            member_queue += secs
    buckets = {
        # Router-side routing overhead: the route/failover windows
        # minus the relay legs and hedge wait nested inside them.
        "route": max(route_like - relay_ok - hedge_wait, 0.0),
        "wal_fsync": wal,
        # Wire + framing overhead of the winning legs, net of the
        # member-side work the legs carried.
        "relay": max(relay_ok - member_exec - member_queue, 0.0),
        "hedge_wait": hedge_wait,
        "member_execute": member_exec,
    }
    total = wal + route_like
    accounted = sum(buckets.values())
    result = {
        "artifact": str(path),
        "trace_id": data.get("trace_id"),
        "reason": data.get("reason"),
        "total_seconds": round(total, 6),
        "buckets": {b: round(v, 6) for b, v in buckets.items()},
        "other_seconds": round(max(total - accounted, 0.0), 6),
    }
    if corrupt:
        result["corrupt_lines"] = corrupt
    return result


def _pctl(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]


def cmd_trace(args: argparse.Namespace) -> int:
    if args.trace_command == "analyze":
        return cmd_trace_analyze(args)
    if args.trace_command == "diff":
        return cmd_trace_diff(args)
    return 2


def cmd_trace_analyze(args: argparse.Namespace) -> int:
    """Per-request latency attribution from trace/postmortem artifacts:
    one file → its critical-path breakdown; a directory → p50/p99 per
    bucket over every span-shaped artifact in it."""
    fleet = bool(getattr(args, "fleet", False))
    analyze = _analyze_fleet_artifact if fleet else _analyze_artifact
    order = FLEET_PATH_BUCKETS if fleet else CRITICAL_PATH_BUCKETS
    path = pathlib.Path(args.artifact)
    since_s = None
    if getattr(args, "since", None):
        try:
            since_s = _parse_duration(args.since)
        except (ValueError, KeyError):
            print(f"error: bad --since duration {args.since!r} "
                  f"(want e.g. 90s, 15m, 2h, 1d)", file=sys.stderr)
            return 2
    if path.is_dir():
        candidates = sorted(list(path.glob("*.json"))
                            + list(path.glob("*.jsonl")))
        if since_s is not None:
            cutoff = time.time() - since_s
            aged = len(candidates)
            candidates = [p for p in candidates
                          if p.stat().st_mtime >= cutoff]
            aged -= len(candidates)
        else:
            aged = 0
        results, skipped, corrupt_lines = [], 0, 0
        for p in candidates:
            r = analyze(p)
            if r is None:
                skipped += 1
                continue
            corrupt_lines += int(r.pop("corrupt_lines", 0) or 0)
            results.append(r)
        if skipped or corrupt_lines:
            # Rotated/chaos-era dirs legitimately hold truncated or
            # corrupt artifacts; report what was passed over instead
            # of crashing on it or hiding it.
            parts = []
            if skipped:
                parts.append(f"{skipped} corrupt/non-span artifact(s)")
            if corrupt_lines:
                parts.append(f"{corrupt_lines} corrupt JSONL line(s)")
            print(f"note: skipped {', '.join(parts)} under {path}",
                  file=sys.stderr)
        if not results:
            print(f"error: no span-shaped artifacts under {path}"
                  + (f" within the last {args.since}" if since_s is not None
                     and aged else ""),
                  file=sys.stderr)
            return 1
        summary = {
            "requests": len(results),
            "skipped": skipped,
            "corrupt_lines": corrupt_lines,
            "p50": {}, "p99": {},
            "results": results,
        }
        for bucket in order + ("other_seconds", "total_seconds"):
            vals = [r["buckets"].get(bucket, r.get(bucket, 0.0))
                    if bucket in order else r.get(bucket, 0.0)
                    for r in results]
            summary["p50"][bucket] = round(_pctl(vals, 0.50), 6)
            summary["p99"][bucket] = round(_pctl(vals, 0.99), 6)
        if args.json:
            print(json.dumps(summary, indent=2))
            return 0
        what = "router-hop path" if fleet else "critical path"
        print(f"{what} over {len(results)} request artifact(s):")
        print(f"{'bucket':<14} {'p50 ms':>10} {'p99 ms':>10}")
        for bucket in order + ("other_seconds", "total_seconds"):
            label = bucket.replace("_seconds", "")
            print(f"{label:<14} {summary['p50'][bucket] * 1e3:>10.1f} "
                  f"{summary['p99'][bucket] * 1e3:>10.1f}")
        return 0
    if not path.is_file():
        print(f"error: no artifact at {path}", file=sys.stderr)
        return 1
    result = analyze(path)
    if result is None:
        print(f"error: {path} is not a span-shaped trace or postmortem "
              f"artifact", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(result, indent=2))
        return 0
    tid = result.get("trace_id") or "-"
    print(f"trace {tid}: total {result['total_seconds'] * 1e3:.1f} ms")
    print(f"{'bucket':<14} {'ms':>10} {'share':>7}")
    total = result["total_seconds"] or 1.0
    for bucket in order:
        v = result["buckets"][bucket]
        print(f"{bucket:<14} {v * 1e3:>10.1f} {v / total:>6.1%}")
    v = result["other_seconds"]
    print(f"{'other':<14} {v * 1e3:>10.1f} {v / total:>6.1%}")
    return 0


def _artifact_phases(path: pathlib.Path) -> tuple[dict | None, str]:
    """Per-phase wall seconds of one artifact, plus its display id.
    Accepts span-shaped artifacts (trace / fleet trace / postmortem)
    and triage bundles (whose ``offender.phases_ms`` is already a
    phase map)."""
    data, _corrupt = _load_span_artifact(path)
    if not isinstance(data, dict):
        return None, "-"
    tid = str(data.get("trace_id") or "-")
    triage = data.get("triage")
    if isinstance(triage, dict) and isinstance(
            triage.get("offender"), dict):
        phases_ms = triage["offender"].get("phases_ms") or {}
        return ({str(k): float(v) / 1000.0 for k, v in phases_ms.items()},
                str(triage["offender"].get("trace_id") or tid))
    spans = data.get("spans")
    if not isinstance(spans, list):
        return None, tid
    phases: dict = {}
    for row in spans:
        if not isinstance(row, dict):
            continue
        name = str(row.get("name") or "?")
        try:
            phases[name] = phases.get(name, 0.0) + \
                float(row.get("seconds") or 0.0)
        except (TypeError, ValueError):
            continue
    return phases, tid


def cmd_trace_diff(args: argparse.Namespace) -> int:
    """Phase-aligned diff of two artifacts — the manual-attribution
    twin of the anomaly auto-triage bundle (same diff rows, same
    suspect_phase semantics, via :func:`obs.anomaly.phase_diff`)."""
    from .obs import anomaly as obs_anomaly
    path_a, path_b = pathlib.Path(args.a), pathlib.Path(args.b)
    for path in (path_a, path_b):
        if not path.is_file():
            print(f"error: no artifact at {path}", file=sys.stderr)
            return 1
    a_phases, a_id = _artifact_phases(path_a)
    b_phases, b_id = _artifact_phases(path_b)
    if a_phases is None or b_phases is None:
        bad = path_a if a_phases is None else path_b
        print(f"error: {bad} is not a span-shaped trace artifact",
              file=sys.stderr)
        return 1
    diff = obs_anomaly.phase_diff(a_phases, b_phases)
    result = {"a": {"artifact": str(path_a), "trace_id": a_id},
              "b": {"artifact": str(path_b), "trace_id": b_id},
              "suspect_phase": diff["suspect_phase"],
              "phases": diff["phases"]}
    if args.json:
        print(json.dumps(result, indent=2))
        return 0
    print(f"trace diff  A={a_id}  B={b_id}")
    print(f"{'phase':<24} {'A ms':>10} {'B ms':>10} {'delta':>10} "
          f"{'ratio':>7}")
    for row in diff["phases"]:
        ratio = f"{row['ratio']:.2f}x" if row["ratio"] is not None else "-"
        print(f"{row['phase']:<24} {row['a_ms']:>10.1f} "
              f"{row['b_ms']:>10.1f} {row['delta_ms']:>+10.1f} "
              f"{ratio:>7}")
    if diff["suspect_phase"]:
        print(f"suspect phase: {diff['suspect_phase']}")
    return 0


def _top_fetch(socket_path: str | None) -> dict:
    """One poll of the dashboard's data: daemon/router status, plus
    member statuses through the router when the target is a fleet."""
    from .service.client import call_control
    status = call_control("status", path=socket_path)
    members = None
    if status.get("fleet"):
        try:
            members = call_control("member_status",
                                   path=socket_path).get("members")
        except Exception:
            members = None
    return {"status": status, "members": members}


def _render_top_frame(snap: dict) -> str:
    """One dashboard screen from a `_top_fetch` snapshot."""
    status = snap["status"]
    lines: List[str] = []
    fleet = bool(status.get("fleet"))
    window = status.get("window") or {}
    w1s, w1m = window.get("1s") or {}, window.get("1m") or {}
    head = "fleet router" if fleet else "merge daemon"
    lines.append(
        f"semmerge top — {head} pid {status.get('pid')}  "
        f"uptime {status.get('uptime_s', 0):.0f}s  "
        f"socket {status.get('socket')}")
    lines.append(
        f"  qps {w1s.get('qps', 0):>7.1f}/s (1s) {w1m.get('qps', 0):>7.2f}/s (1m)   "
        f"p50 {w1m.get('p50_ms', 0):>8.1f} ms   "
        f"p99 {w1m.get('p99_ms', 0):>8.1f} ms   "
        f"err {w1m.get('error_rate', 0):>6.2%}")
    res = status.get("resilience") or {}
    breakers = res.get("breakers") or {}
    tripped = sorted(n for n, s in breakers.items() if s != "closed")
    lines.append(
        f"  queue {status.get('queue_depth', 0):>3}  "
        f"in-flight {status.get('in_flight', 0):>3}  "
        f"served {status.get('served_total', 0):>6}  "
        f"pressure {res.get('pressure', '-')}  "
        f"breakers {('OPEN:' + ','.join(tripped)) if tripped else 'closed'}")
    residency = status.get("residency") or {}
    r_hit = (f"{residency.get('hit_rate', 0.0):.1%}"
             if residency.get("lookups") else "-")
    batch = status.get("batch") or {}
    mesh = batch.get("mesh") or {}
    mesh_occ = mesh.get("last_rows_per_chip")
    sampling = status.get("sampling") or {}
    store = status.get("trace_store") or {}
    lines.append(
        f"  residency hit {r_hit}  "
        f"mesh occupancy {mesh_occ if mesh_occ is not None else '-'}  "
        f"sampling {'on' if sampling.get('enabled') else 'keep-all'}  "
        f"trace store {store.get('count', '-')} files"
        + (f" ({store.get('bytes', 0) / 1048576.0:.1f}/"
           f"{store.get('budget_bytes', 0) / 1048576.0:.0f} MB)"
           if store else ""))
    anomaly = status.get("anomaly") or {}
    if anomaly.get("latched"):
        lines.append(f"  ANOMALY latched: {', '.join(anomaly['latched'])}"
                     f"  (bundles fired: {anomaly.get('fired', 0)})")
    slo = status.get("slo")
    if isinstance(slo, dict):
        lines.append(f"  slo {'HEALTHY' if slo.get('healthy', True) else 'BURNING'}")
    members = snap.get("members")
    if fleet:
        lines.append("")
        lines.append(f"  {'member':<8} {'state':<10} {'qps(1m)':>8} "
                     f"{'p99 ms':>8} {'queue':>6} {'in-fl':>6} "
                     f"{'served':>7}")
        rows = status.get("members") or []
        by_id = {}
        if isinstance(members, dict):
            by_id = {mid: m for mid, m in members.items()
                     if isinstance(m, dict)}
        for view in rows:
            if not isinstance(view, dict):
                continue
            mid = str(view.get("id") or "?")
            mstat = by_id.get(mid) or {}
            mwin = (mstat.get("window") or {}).get("1m") or {}
            lines.append(
                f"  {mid:<8} {str(view.get('state', '?')):<10} "
                f"{mwin.get('qps', 0):>8.2f} "
                f"{mwin.get('p99_ms', 0):>8.1f} "
                f"{mstat.get('queue_depth', 0):>6} "
                f"{mstat.get('in_flight', 0):>6} "
                f"{mstat.get('served_total', 0):>7}")
    return "\n".join(lines)


def cmd_top(args: argparse.Namespace) -> int:
    """Live one-screen dashboard. Interactive on a TTY (q quits,
    p pauses); ``--once`` (or a non-TTY stdout) prints one frame and
    exits, so scripts and tests get a stable surface."""
    from .service.client import DaemonUnavailable
    interactive = (not args.once and sys.stdout.isatty()
                   and sys.stdin.isatty())
    try:
        snap = _top_fetch(args.socket)
    except DaemonUnavailable as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if not interactive:
        if args.json:
            print(json.dumps(snap, indent=2, default=str))
        else:
            print(_render_top_frame(snap))
        return 0
    import select
    import termios
    import tty
    fd = sys.stdin.fileno()
    old_attrs = termios.tcgetattr(fd)
    paused = False
    try:
        tty.setcbreak(fd)
        while True:
            if not paused:
                try:
                    snap = _top_fetch(args.socket)
                    frame = _render_top_frame(snap)
                except DaemonUnavailable as exc:
                    frame = f"daemon unreachable: {exc}"
                sys.stdout.write("\x1b[2J\x1b[H" + frame
                                 + "\n\n  q quit · p pause\n")
                sys.stdout.flush()
            ready, _, _ = select.select([fd], [], [],
                                        max(0.2, args.interval))
            if ready:
                key = os.read(fd, 1).decode("utf-8", "replace").lower()
                if key == "q":
                    return 0
                if key == "p":
                    paused = not paused
    except KeyboardInterrupt:
        return 0
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old_attrs)
        sys.stdout.write("\n")


def cmd_train_matcher(args: argparse.Namespace) -> int:
    from .models.training import TrainConfig, train_matcher
    cfg = TrainConfig(steps=args.steps, batch=args.batch, seq=args.seq,
                      seed=args.seed, ckpt_dir=args.ckpt_dir,
                      ckpt_every=args.ckpt_every)
    _, _, loss, ran = train_matcher(cfg, resume=not args.no_resume)
    where = f", checkpoints in {args.ckpt_dir}" if args.ckpt_dir else ""
    if ran == 0:  # e.g. resumed at or past --steps
        print(f"nothing to do: checkpoint already at step {args.steps}{where}")
    else:
        print(f"trained {ran} steps, final loss {loss:.4f}{where}")
    if args.eval:
        # Held-out pairing precision/recall, from the checkpoint just
        # written (or seeded params when no --ckpt-dir — reported with
        # trained=false so the number cannot masquerade as quality).
        from .models.evaluate import evaluate_matcher
        from .models.signature import EmbeddingSignatureMatcher
        matcher = EmbeddingSignatureMatcher(ckpt_dir=args.ckpt_dir,
                                            allow_untrained=True)
        print(json.dumps({"matcher_eval": evaluate_matcher(matcher)}))
    return 0


def _write_conflict_reports(conflicts: Sequence[object],
                            resolutions: Sequence[dict] | None = None) -> None:
    from .core.conflict import conflicts_payload
    payload = conflicts_payload(conflicts, resolutions)
    _conflicts_path().write_text(json.dumps(payload, indent=2),
                                 encoding="utf-8")


def _cleanup(paths: Iterable[pathlib.Path]) -> None:
    for path in paths:
        try:
            shutil.rmtree(path)
        except (FileNotFoundError, OSError):
            pass


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
