"""Always-on fault flight recorder — a bounded per-process ring of the
most recent span observations, dumped as a postmortem bundle when
something goes wrong.

Motivation: the span layer (:mod:`semantic_merge_tpu.obs.spans`) builds
full :class:`~semantic_merge_tpu.obs.spans.SpanRecord` objects only
while a recorder is active, so a fault in an uninstrumented run — no
``--trace``, no daemon ``--events`` — historically left *zero*
span-level evidence. The flight recorder closes that gap: every
``span()``/``record()`` completion also appends one small dict to a
ring buffer here (the same call sites that feed the phase histogram
unconditionally), and :func:`dump` serializes the ring plus the fault
chain, breaker states, metrics registry, and an environment fingerprint
into ``.semmerge-postmortem/<trace_id>.json`` whenever a ``MergeFault``
escapes a ladder rung, a circuit breaker transitions, or the supervisor
respawns the daemon.

Knobs:

- ``SEMMERGE_FLIGHT_SPANS`` — ring capacity (default 512; ``0``
  disables capture, bundles then carry an empty ``spans`` array).
- ``SEMMERGE_POSTMORTEM_DIR`` — override the bundle directory (the
  default is ``.semmerge-postmortem/`` under the caller-provided root,
  typically the merge repo's work tree).
- ``SEMMERGE_POSTMORTEM_KEEP`` / ``SEMMERGE_POSTMORTEM_BUDGET_MB`` —
  retention caps on the bundle directory (default 64 bundles / 64 MB;
  ``0`` disables a cap). The directory was append-forever before PR 20;
  now every dump prunes oldest-first past either cap and counts the
  evictions in ``postmortem_pruned_total``.

Import cost stays trivial (stdlib only — the :mod:`obs` package
contract); the per-span cost is one dict build and a deque append
under a lock.
"""
from __future__ import annotations

import json
import os
import pathlib
import platform
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import metrics

#: Ring-capacity env knob (number of retained span observations).
ENV_RING = "SEMMERGE_FLIGHT_SPANS"
#: Bundle directory override (absolute path wins over ``root``).
ENV_DIR = "SEMMERGE_POSTMORTEM_DIR"
#: Default ring capacity.
DEFAULT_RING = 512
#: Bundle directory name (relative to the dump root).
POSTMORTEM_DIR = ".semmerge-postmortem"
#: Bundle schema version (``scripts/check_trace_schema.py
#: validate_postmortem`` pins the shape).
POSTMORTEM_SCHEMA = 1
#: Documented ``reason`` values a bundle may carry.
REASONS = ("fault-escape", "degradation", "breaker-transition",
           "supervisor-restart", "daemon-drain", "slo-burn",
           "resolver-fault", "fleet-failover", "anomaly")

#: Retention caps for the bundle directory.
ENV_KEEP = "SEMMERGE_POSTMORTEM_KEEP"
ENV_BUDGET_MB = "SEMMERGE_POSTMORTEM_BUDGET_MB"
DEFAULT_KEEP = 64
DEFAULT_BUDGET_MB = 64.0

_lock = threading.Lock()
_ring: Optional[deque] = None
_ring_capacity: Optional[int] = None
_epoch = time.perf_counter()


def ring_capacity() -> int:
    """Configured ring size (``SEMMERGE_FLIGHT_SPANS``, default 512)."""
    raw = os.environ.get(ENV_RING, "").strip()
    if not raw:
        return DEFAULT_RING
    try:
        return max(0, int(float(raw)))
    except ValueError:
        return DEFAULT_RING


def reset() -> None:
    """Drop the ring and re-read the capacity env (tests)."""
    global _ring, _ring_capacity
    with _lock:
        _ring = None
        _ring_capacity = None


def _get_ring() -> Optional[deque]:
    global _ring, _ring_capacity
    if _ring_capacity is None:
        with _lock:
            if _ring_capacity is None:
                _ring_capacity = ring_capacity()
                _ring = deque(maxlen=_ring_capacity) if _ring_capacity \
                    else None
    return _ring


def note(name: str, seconds: float, *, layer: Optional[str] = None,
         status: str = "ok", error: Optional[str] = None,
         trace_id: Optional[str] = None,
         meta: Optional[Dict[str, Any]] = None) -> None:
    """Append one span observation to the ring. Called by
    ``obs.spans`` for every completed span/record — with or without an
    active recorder — so keep this cheap and never let it raise."""
    ring = _get_ring()
    if ring is None:
        return
    row = {
        "name": name,
        "t": round(time.perf_counter() - _epoch, 6),
        "seconds": round(seconds, 6),
        "layer": layer,
        "status": status,
        "error": error,
        "trace_id": trace_id,
        "thread": threading.current_thread().name,
        "meta": dict(meta) if meta else {},
    }
    with _lock:
        ring.append(row)


def snapshot() -> List[dict]:
    """The retained observations, oldest first."""
    ring = _get_ring()
    if ring is None:
        return []
    with _lock:
        return list(ring)


def _fault_payload(fault: Optional[BaseException]) -> Optional[dict]:
    if fault is None:
        return None
    return {
        "type": type(fault).__name__,
        "message": str(fault),
        "stage": getattr(fault, "stage", None),
        "cause": getattr(fault, "cause", None),
        "exit_code": getattr(fault, "exit_code", None),
    }


def _fault_chain(fault: Optional[BaseException]) -> List[str]:
    chain: List[str] = []
    seen = set()
    exc = fault
    while exc is not None and id(exc) not in seen and len(chain) < 16:
        seen.add(id(exc))
        chain.append(f"{type(exc).__name__}: {exc}")
        exc = exc.__cause__ or exc.__context__
    return chain


def _env_fingerprint() -> dict:
    return {
        "pid": os.getpid(),
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "argv": list(sys.argv[:6]),
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith("SEMMERGE_") or k == "_SEMMERGE_IN_DAEMON"},
    }


def default_trace_id() -> str:
    """A local id for dumps that happen outside any request scope
    (one-shot CLI runs, daemon-level events)."""
    return f"local-{os.getpid():x}-{os.urandom(4).hex()}"


def dump(trace_id: Optional[str], reason: str, *,
         fault: Optional[BaseException] = None,
         breakers: Optional[Dict[str, str]] = None,
         root: Optional[pathlib.Path | str] = None,
         extra: Optional[Dict[str, Any]] = None) -> Optional[pathlib.Path]:
    """Write a postmortem bundle; return its path, or ``None`` when the
    bundle cannot be written (dumping must never add a second failure
    to the one being recorded)."""
    tid = trace_id or default_trace_id()
    safe = "".join(ch if ch.isalnum() or ch in "._-" else "-"
                   for ch in str(tid))[:80] or "unknown"
    try:
        override = os.environ.get(ENV_DIR, "").strip()
        if override:
            out_dir = pathlib.Path(override)
        else:
            out_dir = pathlib.Path(root) / POSTMORTEM_DIR if root \
                else pathlib.Path.cwd() / POSTMORTEM_DIR
        out_dir.mkdir(parents=True, exist_ok=True)
        bundle = {
            "schema": POSTMORTEM_SCHEMA,
            "trace_id": str(tid),
            "reason": reason,
            "ts": round(time.time(), 3),
            "spans": snapshot(),
            "fault": _fault_payload(fault),
            "fault_chain": _fault_chain(fault),
            "breakers": dict(breakers) if breakers else {},
            "metrics": metrics.REGISTRY.to_dict(),
            "env": _env_fingerprint(),
        }
        if extra:
            bundle.update(extra)
        path = out_dir / f"{safe}.json"
        path.write_text(json.dumps(bundle, indent=2, default=str),
                        encoding="utf-8")
        metrics.REGISTRY.counter(
            "postmortem_bundles_total",
            "Postmortem flight-recorder bundles written, by reason").inc(
                1, reason=reason)
        _prune_bundles(out_dir)
        return path
    except Exception:
        return None


def _cap(env: str, default: float) -> Optional[float]:
    """Parse a retention cap; ``0`` (or negative) disables it."""
    raw = os.environ.get(env, "").strip()
    try:
        value = float(raw) if raw else default
    except ValueError:
        value = default
    return value if value > 0 else None


def _prune_bundles(out_dir: pathlib.Path) -> int:
    """Enforce the bundle-directory retention caps (oldest first)."""
    from . import sampling  # local import: keep module import cost flat
    keep = _cap(ENV_KEEP, DEFAULT_KEEP)
    budget = _cap(ENV_BUDGET_MB, DEFAULT_BUDGET_MB)
    return sampling.prune_dir(
        out_dir,
        max_count=int(keep) if keep is not None else None,
        max_bytes=int(budget * 1024 * 1024) if budget is not None else None,
        counter="postmortem_pruned_total",
        dir=str(out_dir.name))
