"""Process-global metrics registry.

The shared numeric spine of the observability layer
(:mod:`semantic_merge_tpu.obs`): counters, gauges, and fixed-bucket
histograms with label support, renderable as Prometheus text exposition
and as JSON. Every instrumented layer (frontend scanner, compose
kernels, fused merge engine, parallel paths, backends, runtime applier)
records here unconditionally — recording is a dict update under a lock,
cheap enough to leave always-on — and three consumers read it:

- ``bench.py`` derives its ``phases_ms`` from :func:`phase_totals`
  deltas, so BENCH JSON and CLI ``--trace`` artifacts share one timing
  code path instead of hand-rolled ``phases`` dicts;
- the :class:`~semantic_merge_tpu.runtime.trace.Tracer` embeds
  :meth:`Registry.to_dict` into ``.semmerge-trace.json``;
- ``SEMMERGE_METRICS=path`` dumps the registry on interpreter exit
  (JSON, or Prometheus text when the path ends in ``.prom``), and the
  ``semmerge stats`` subcommand pretty-prints either form.

Semantics follow the Prometheus data model: histogram buckets are
cumulative upper bounds (a value lands in every bucket whose ``le`` it
does not exceed), ``_sum``/``_count`` accompany each labeled series.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

#: Default bucket ladder for phase wall-times (seconds): sub-ms host
#: hops up to the reference's 40 s cold-start budget.
PHASE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 40.0)

#: Byte-size ladder for transfer histograms.
BYTE_BUCKETS = (256.0, 1024.0, 4096.0, 16384.0, 65536.0, 262144.0,
                1048576.0, 4194304.0, 16777216.0, 67108864.0)


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


#: Per-metric series-cardinality budget (``SEMMERGE_METRICS_MAX_SERIES``,
#: default 512; ``0`` disables the cap). At production QPS unbounded
#: label sets (per-repo, per-member, per-category) make the registry
#: itself the outage — past the budget, NEW label sets collapse into one
#: overflow series and ``metrics_series_dropped_total`` counts them.
ENV_MAX_SERIES = "SEMMERGE_METRICS_MAX_SERIES"
DEFAULT_MAX_SERIES = 512
OVERFLOW_KEY: LabelKey = (("overflow", "true"),)
SERIES_DROPPED = "metrics_series_dropped_total"


def series_budget() -> int:
    raw = os.environ.get(ENV_MAX_SERIES, "").strip()
    if not raw:
        return DEFAULT_MAX_SERIES
    try:
        return max(0, int(float(raw)))
    except ValueError:
        return DEFAULT_MAX_SERIES


def _note_series_dropped(metric_name: str) -> None:
    if metric_name == SERIES_DROPPED:  # the counter never recurses
        return
    REGISTRY.counter(
        SERIES_DROPPED,
        "New label sets rejected by the per-metric cardinality budget"
    ).inc(1, metric=metric_name)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "") -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, object] = {}

    def _admit(self, key: LabelKey) -> LabelKey:
        """Cardinality gate (caller holds ``self._lock``): an existing
        series always records; a NEW one past the budget is rerouted to
        the overflow series so hot paths stay bounded either way."""
        if key in self._series or key == OVERFLOW_KEY:
            return key
        budget = series_budget()
        if budget <= 0 or len(self._series) < budget:
            return key
        _note_series_dropped(self.name)
        return OVERFLOW_KEY

    def _labelled(self) -> List[Tuple[LabelKey, object]]:
        with self._lock:
            return list(self._series.items())


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels: object) -> None:
        key = _label_key(labels)
        with self._lock:
            key = self._admit(key)
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels: object) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: object) -> None:
        with self._lock:
            self._series[self._admit(_label_key(labels))] = float(value)

    def max(self, value: float, **labels: object) -> None:
        """High-water-mark update: keep the larger of current/new."""
        key = _label_key(labels)
        with self._lock:
            key = self._admit(key)
            prev = self._series.get(key)
            if prev is None or value > prev:
                self._series[key] = float(value)

    def value(self, **labels: object) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Iterable[float] = PHASE_BUCKETS) -> None:
        super().__init__(name, help)
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name!r} needs at least one bucket")

    def observe(self, value: float, exemplar: object = None,
                **labels: object) -> None:
        key = _label_key(labels)
        with self._lock:
            key = self._admit(key)
            series = self._series.get(key)
            if series is None:
                # counts has one slot per finite bucket plus +Inf.
                series = {"counts": [0] * (len(self.buckets) + 1),
                          "sum": 0.0, "count": 0}
                self._series[key] = series
            # Cumulative-upper-bound semantics: the first bucket whose
            # bound is >= value owns the observation (bisect_left puts a
            # value exactly on a bound INTO that bound's bucket).
            idx = bisect_left(self.buckets, value)
            series["counts"][idx] += 1
            series["sum"] += value
            series["count"] += 1
            if exemplar is not None:
                # Per-bucket exemplars (OpenMetrics): each bucket keeps
                # its own most recent trace_id, so a p99 outlier's id
                # survives the stream of p50 observations that follows.
                series.setdefault("exemplars", {})[idx] = {
                    "trace_id": str(exemplar), "value": float(value)}

    def sum(self, **labels: object) -> float:
        with self._lock:
            series = self._series.get(_label_key(labels))
            return float(series["sum"]) if series else 0.0

    def label_sums(self) -> Dict[LabelKey, float]:
        with self._lock:
            return {k: float(v["sum"]) for k, v in self._series.items()}

    def snapshot(self, **labels: object) -> Dict[str, object]:
        """Copy of one series (``counts``/``sum``/``count``) — empty
        zeros when the labelled series was never observed."""
        with self._lock:
            series = self._series.get(_label_key(labels))
            if series is None:
                return {"counts": [0] * (len(self.buckets) + 1),
                        "sum": 0.0, "count": 0}
            return {"counts": list(series["counts"]),
                    "sum": float(series["sum"]),
                    "count": int(series["count"])}

    def quantile(self, q: float, **labels: object) -> float:
        """Estimated ``q``-quantile of one labelled series via
        :func:`histogram_quantile`."""
        snap = self.snapshot(**labels)
        return histogram_quantile(self.buckets, snap["counts"], q)


class Registry:
    """Named metric store. ``counter``/``gauge``/``histogram`` are
    get-or-create: re-registering a name returns the existing metric
    (a kind mismatch raises — two layers disagreeing about a metric's
    type is a bug worth failing loudly on)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: "Dict[str, _Metric]" = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}")
                return existing
            metric = cls(name, help, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = PHASE_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        """Drop every metric — test isolation only."""
        with self._lock:
            self._metrics.clear()

    def to_dict(self) -> dict:
        """JSON form: the schema ``scripts/check_trace_schema.py``
        validates and ``render_prometheus_from_dict`` renders — the
        round-trip contract."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            if isinstance(m, Histogram):
                out["histograms"][m.name] = {
                    "help": m.help,
                    "buckets": list(m.buckets),
                    "series": [
                        dict({"labels": dict(key),
                              "counts": list(s["counts"]),
                              "sum": s["sum"], "count": s["count"]},
                             **({"exemplars": {str(i): dict(e)
                                               for i, e in
                                               sorted(s["exemplars"].items())}}
                                if s.get("exemplars") else {}))
                        for key, s in m._labelled()
                    ],
                }
            else:
                bucket = out["counters" if isinstance(m, Counter) else "gauges"]
                bucket[m.name] = {
                    "help": m.help,
                    "series": [{"labels": dict(key), "value": v}
                               for key, v in m._labelled()],
                }
        return out

    def render_prometheus(self) -> str:
        return render_prometheus_from_dict(self.to_dict())


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def render_prometheus_from_dict(data: dict) -> str:
    """Prometheus text exposition (format 0.0.4) of a
    :meth:`Registry.to_dict` payload. A module function (not a method)
    so ``semmerge stats --prometheus`` can render archived artifacts
    from processes long gone."""
    lines: List[str] = []
    for kind in ("counters", "gauges"):
        for name in sorted(data.get(kind, ())):
            m = data[kind][name]
            if m.get("help"):
                lines.append(f"# HELP {name} {m['help']}")
            lines.append(f"# TYPE {name} {'counter' if kind == 'counters' else 'gauge'}")
            for s in m["series"]:
                lines.append(f"{name}{_fmt_labels(s['labels'])} "
                             f"{_fmt_value(s['value'])}")
    for name in sorted(data.get("histograms", ())):
        m = data["histograms"][name]
        if m.get("help"):
            lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} histogram")
        bounds = [_fmt_value(b) for b in m["buckets"]] + ["+Inf"]
        for s in m["series"]:
            cum = 0
            for bound, count in zip(bounds, s["counts"]):
                cum += count
                le = 'le="%s"' % bound
                lines.append(f"{name}_bucket{_fmt_labels(s['labels'], le)} "
                             f"{cum}")
            lines.append(f"{name}_sum{_fmt_labels(s['labels'])} "
                         f"{_fmt_value(s['sum'])}")
            lines.append(f"{name}_count{_fmt_labels(s['labels'])} "
                         f"{s['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


#: The process-global registry every instrumented layer records into.
REGISTRY = Registry()


def registry() -> Registry:
    return REGISTRY


# ---------------------------------------------------------------------------
# Quantile estimation over fixed-bucket histograms.

def histogram_quantile(buckets: Iterable[float], counts: Iterable[int],
                       q: float) -> float:
    """Prometheus-style quantile interpolation over one histogram
    series.

    ``buckets`` are the finite upper bounds; ``counts`` are the
    **non-cumulative** per-bucket observation counts with one extra
    trailing slot for the +Inf overflow bucket (the in-memory
    :class:`Histogram` layout and the ``to_dict`` wire shape). The
    estimate linearly interpolates within the bucket that holds the
    target rank, assuming observations spread uniformly between the
    bucket's lower and upper bound — the same model Prometheus's
    ``histogram_quantile()`` uses, so daemon-side SLO math agrees with
    dashboard math. Values landing in the overflow bucket clamp to the
    highest finite bound (the estimate cannot exceed what the ladder
    can resolve). Empty series return ``0.0``.
    """
    bounds = tuple(buckets)
    counts = list(counts)
    if len(counts) != len(bounds) + 1:
        raise ValueError(
            f"counts length {len(counts)} != len(buckets)+1 "
            f"({len(bounds) + 1})")
    total = sum(counts)
    if total <= 0:
        return 0.0
    q = min(1.0, max(0.0, float(q)))
    rank = q * total
    cum = 0
    for i, count in enumerate(counts):
        prev_cum = cum
        cum += count
        if cum >= rank and count > 0:
            if i >= len(bounds):
                # Overflow bucket: unbounded above — clamp.
                return float(bounds[-1])
            lower = bounds[i - 1] if i > 0 else 0.0
            upper = bounds[i]
            frac = (rank - prev_cum) / count
            return float(lower + (upper - lower) * frac)
    return float(bounds[-1])


# ---------------------------------------------------------------------------
# Phase timing — the spine shared by spans, --trace, and bench.py.

PHASE_HISTOGRAM = "semmerge_phase_seconds"


def observe_phase(name: str, seconds: float) -> None:
    REGISTRY.histogram(
        PHASE_HISTOGRAM, "Wall seconds per instrumented pipeline phase",
        buckets=PHASE_BUCKETS).observe(seconds, phase=name)


def phase_totals() -> Dict[str, float]:
    """Cumulative wall seconds per phase name since process start."""
    hist = REGISTRY.histogram(PHASE_HISTOGRAM,
                              "Wall seconds per instrumented pipeline phase",
                              buckets=PHASE_BUCKETS)
    out: Dict[str, float] = {}
    for key, total in hist.label_sums().items():
        labels = dict(key)
        out[labels.get("phase", "?")] = out.get(labels.get("phase", "?"),
                                                0.0) + total
    return out


def phase_totals_since(before: Dict[str, float]) -> Dict[str, float]:
    """Per-phase wall-seconds delta against a :func:`phase_totals`
    snapshot — how ``bench.py`` scopes one instrumented merge out of a
    process that has already run warmups and parity gates."""
    now = phase_totals()
    out = {}
    for name, total in now.items():
        delta = total - before.get(name, 0.0)
        if delta > 0.0:
            out[name] = delta
    return out


# ---------------------------------------------------------------------------
# Exit dump (SEMMERGE_METRICS=path)

def dump(path: str) -> None:
    """Write the registry to ``path``: Prometheus text when the name
    ends in ``.prom``, JSON otherwise."""
    if str(path).endswith(".prom"):
        payload = REGISTRY.render_prometheus()
    else:
        payload = json.dumps(REGISTRY.to_dict(), indent=2)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(payload)


def _install_exit_dump() -> None:
    path = os.environ.get("SEMMERGE_METRICS")
    if not path:
        return

    def _dump_at_exit() -> None:
        try:
            dump(path)
        except OSError:  # dumping diagnostics must never mask an exit
            pass

    atexit.register(_dump_at_exit)


_install_exit_dump()
