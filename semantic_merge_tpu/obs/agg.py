"""Streaming windowed aggregation — 1 s / 1 m rollups of request
latency and phase time, built on mergeable log-bucketed quantile
sketches.

The raw registry (:mod:`semantic_merge_tpu.obs.metrics`) is cumulative
since process start — good for totals, useless for "what is p99 *right
now*". This module keeps a ring of per-second slots, each holding a
:class:`QuantileSketch` plus error/phase/verb tallies; reading a window
merges the relevant slots. Sketches are mergeable by construction
(bucket-wise count addition), which is also what lets a router fold
member-shipped sketches into one fleet-wide estimate without holding
raw samples.

The sketch is DDSketch-shaped: value ``v`` lands in bucket
``ceil(log(v) / log(gamma))`` with ``gamma = (1+alpha)/(1-alpha)``,
giving a relative quantile-error guarantee of ``alpha`` (default 1%).
Memory is one small int-keyed dict per slot — bounded by the dynamic
range of observed latencies, not by their volume.

Consumers: the daemon's ``status()`` grows a ``window`` block, and
``/metrics`` exposes ``semmerge_window_*`` gauges that ``semmerge top``
polls fleet-wide. Import cost stays stdlib-only.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import metrics

#: Default relative accuracy of the sketch (1%).
DEFAULT_ALPHA = 0.01
#: Values below this collapse into the zero bucket.
MIN_TRACKED = 1e-9
#: Per-slot cap on distinct phase keys (the phase namespace is small
#: and closed today; the cap is a safety rail, not a tuning knob).
MAX_PHASES_PER_SLOT = 64
#: 1-second slots retained (>= the 1m window plus slack).
RING_SECONDS = 120

WINDOWS = ("1s", "1m")


class QuantileSketch:
    """Log-bucketed quantile sketch with exact-merge semantics.

    ``merge(a, b)`` is bucket-wise addition, so a merged sketch answers
    quantiles over the union stream with the same ``alpha`` guarantee
    as either input — the property test in ``tests/test_agg.py`` pins
    this. Not thread-safe; callers hold their own locks."""

    __slots__ = ("alpha", "_gamma", "_log_gamma", "zero", "buckets",
                 "count", "sum", "min", "max")

    def __init__(self, alpha: float = DEFAULT_ALPHA) -> None:
        self.alpha = float(alpha)
        self._gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._log_gamma = math.log(self._gamma)
        self.zero = 0
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += max(0.0, v)
        if v < self.min:
            self.min = max(0.0, v)
        if v > self.max:
            self.max = v
        if v <= MIN_TRACKED:
            self.zero += 1
            return
        key = math.ceil(math.log(v) / self._log_gamma)
        self.buckets[key] = self.buckets.get(key, 0) + 1

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """In-place bucket-wise merge; returns self. Requires equal
        ``alpha`` (mixed-resolution merges would silently lose the
        error guarantee)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError("cannot merge sketches with different alpha")
        self.zero += other.zero
        for key, n in other.buckets.items():
            self.buckets[key] = self.buckets.get(key, 0) + n
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def quantile(self, q: float) -> float:
        """``q``-quantile estimate (midpoint of the owning bucket);
        ``0.0`` on an empty sketch."""
        if self.count <= 0:
            return 0.0
        q = min(1.0, max(0.0, float(q)))
        rank = q * (self.count - 1) + 1
        if rank <= self.zero:
            return 0.0
        cum = self.zero
        for key in sorted(self.buckets):
            cum += self.buckets[key]
            if cum >= rank:
                upper = self._gamma ** key
                return 2.0 * upper / (self._gamma + 1.0)
        return self.max

    def to_dict(self) -> Dict[str, Any]:
        return {"alpha": self.alpha, "zero": self.zero,
                "buckets": {str(k): v for k, v in self.buckets.items()},
                "count": self.count, "sum": round(self.sum, 9),
                "max": self.max,
                "min": 0.0 if self.min is math.inf else self.min}

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "QuantileSketch":
        sketch = cls(float(data.get("alpha", DEFAULT_ALPHA)))
        sketch.zero = int(data.get("zero", 0))
        sketch.buckets = {int(k): int(v)
                          for k, v in (data.get("buckets") or {}).items()}
        sketch.count = int(data.get("count", 0))
        sketch.sum = float(data.get("sum", 0.0))
        sketch.max = float(data.get("max", 0.0))
        raw_min = data.get("min", 0.0)
        sketch.min = math.inf if sketch.count == 0 else float(raw_min)
        return sketch


class _Slot:
    __slots__ = ("sec", "count", "errors", "sketch", "phases", "verbs")

    def __init__(self, sec: int, alpha: float) -> None:
        self.sec = sec
        self.count = 0
        self.errors = 0
        self.sketch = QuantileSketch(alpha)
        self.phases: Dict[str, float] = {}
        self.verbs: Dict[str, int] = {}


class WindowAggregator:
    """Ring of 1-second slots rolled up into 1 s / 1 m windows.

    ``observe`` files one finished request (latency + optional per-phase
    seconds) into the current second's slot; ``window()`` merges the
    last *completed* second (``"1s"``) and the trailing 60 completed
    seconds (``"1m"``) into rollups. The clock is injectable so tests
    drive window boundaries deterministically."""

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.alpha = float(alpha)
        self._clock = clock
        self._lock = threading.Lock()
        self._slots: deque = deque(maxlen=RING_SECONDS)

    def _slot(self, sec: int) -> _Slot:
        if self._slots and self._slots[-1].sec == sec:
            return self._slots[-1]
        slot = _Slot(sec, self.alpha)
        self._slots.append(slot)
        return slot

    def observe(self, verb: str, seconds: float, *,
                error: bool = False,
                phases: Optional[Dict[str, float]] = None) -> None:
        sec = int(self._clock())
        with self._lock:
            slot = self._slot(sec)
            slot.count += 1
            if error:
                slot.errors += 1
            slot.sketch.observe(float(seconds))
            slot.verbs[verb] = slot.verbs.get(verb, 0) + 1
            if phases:
                for name, secs in phases.items():
                    if (name not in slot.phases
                            and len(slot.phases) >= MAX_PHASES_PER_SLOT):
                        continue
                    slot.phases[name] = slot.phases.get(name, 0.0) \
                        + float(secs)

    def _roll(self, slots: List[_Slot], span: float) -> Dict[str, Any]:
        sketch = QuantileSketch(self.alpha)
        count = errors = 0
        phases: Dict[str, float] = {}
        verbs: Dict[str, int] = {}
        for slot in slots:
            sketch.merge(slot.sketch)
            count += slot.count
            errors += slot.errors
            for name, secs in slot.phases.items():
                phases[name] = phases.get(name, 0.0) + secs
            for verb, n in slot.verbs.items():
                verbs[verb] = verbs.get(verb, 0) + n
        return {
            "span_s": span,
            "count": count,
            "errors": errors,
            "qps": round(count / span, 4) if span > 0 else 0.0,
            "error_rate": round(errors / count, 6) if count else 0.0,
            "p50_ms": round(1000.0 * sketch.quantile(0.50), 3),
            "p99_ms": round(1000.0 * sketch.quantile(0.99), 3),
            "max_ms": round(1000.0 * sketch.max, 3),
            "phases_ms": {name: round(1000.0 * secs, 3)
                          for name, secs in sorted(phases.items())},
            "verbs": dict(sorted(verbs.items())),
        }

    def window(self) -> Dict[str, Any]:
        """The ``window`` block: ``{"1s": rollup, "1m": rollup}`` over
        completed seconds (the in-progress second is excluded so rates
        are never computed over a partial span)."""
        now_sec = int(self._clock())
        with self._lock:
            slots = list(self._slots)
        return {
            "1s": self._roll([s for s in slots if s.sec == now_sec - 1],
                             1.0),
            "1m": self._roll([s for s in slots
                              if now_sec - 60 <= s.sec <= now_sec - 1],
                             60.0),
        }

    def sketch_for(self, window: str = "1m") -> QuantileSketch:
        """Merged latency sketch over one window — the mergeable unit a
        router folds across members."""
        now_sec = int(self._clock())
        lo = now_sec - (1 if window == "1s" else 60)
        merged = QuantileSketch(self.alpha)
        with self._lock:
            for slot in self._slots:
                if lo <= slot.sec <= now_sec - 1:
                    merged.merge(slot.sketch)
        return merged

    def publish(self, registry: Optional[metrics.Registry] = None) -> None:
        """Mirror the rollups into ``semmerge_window_*`` gauges so
        ``/metrics`` scrapes (and the federated fleet view) carry them."""
        reg = registry or metrics.REGISTRY
        snap = self.window()
        qps = reg.gauge("semmerge_window_qps",
                        "Requests/s over the rollup window")
        p50 = reg.gauge("semmerge_window_p50_ms",
                        "Windowed p50 service latency (ms)")
        p99 = reg.gauge("semmerge_window_p99_ms",
                        "Windowed p99 service latency (ms)")
        err = reg.gauge("semmerge_window_error_rate",
                        "Windowed error fraction")
        for name in WINDOWS:
            roll = snap[name]
            qps.set(roll["qps"], window=name)
            p50.set(roll["p50_ms"], window=name)
            p99.set(roll["p99_ms"], window=name)
            err.set(roll["error_rate"], window=name)
