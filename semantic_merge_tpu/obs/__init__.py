"""Unified observability layer (reference ``requirements.md:182``
[NFR-OBS-002]; ``architecture.md:248-249``).

One instrumentation spine for the whole merge pipeline, three pieces:

- :mod:`~semantic_merge_tpu.obs.spans` — nestable, thread-safe spans
  and events with monotonic wall-time, emitted as JSONL
  (``.semmerge-events.jsonl``) and summarized into
  ``.semmerge-trace.json``. The CLI ``Tracer`` is a thin adapter over a
  :class:`~semantic_merge_tpu.obs.spans.SpanRecorder`.
- :mod:`~semantic_merge_tpu.obs.metrics` — process-global counters,
  gauges, and fixed-bucket histograms with labels; Prometheus text and
  JSON rendering; ``SEMMERGE_METRICS=path`` exit dump. ``bench.py``
  derives its ``phases_ms`` from this registry, so BENCH JSON and CLI
  traces share one timing code path.
- :mod:`~semantic_merge_tpu.obs.device` — JAX backend/platform capture,
  compile-cache counters, host↔device transfer accounting, live-buffer
  high-water marks; attached to the trace artifact.
- :mod:`~semantic_merge_tpu.obs.flight` — always-on bounded ring of
  recent span observations (``SEMMERGE_FLIGHT_SPANS``), dumped as
  ``.semmerge-postmortem/<trace_id>.json`` bundles on fault escape,
  breaker transition, supervisor respawn, or daemon drain.

Import cost is intentionally trivial (stdlib only — no JAX, no numpy),
so every layer can import ``obs`` at module top without touching the
host path's cold-start budget.
"""
from . import device, export, flight, metrics, spans  # noqa: F401
from .metrics import REGISTRY, registry  # noqa: F401
from .spans import (SpanRecorder, activate, activated, active,  # noqa: F401
                    current, deactivate, event, record, record_into,
                    request_scope, span, trace_id)
