"""Sliding-window SLO engine — latency/error objectives evaluated
against live daemon traffic with multi-window burn rates.

The metrics registry (:mod:`semantic_merge_tpu.obs.metrics`) keeps
*cumulative-forever* histograms: perfect for postmortems, useless for
"is the daemon healthy *right now*". This module layers a slot-ring
sliding window on the same fixed bucket ladder: every completed request
lands one observation in the current time slot's per-verb bucket
counts, and :meth:`SloEngine.evaluate` sums the slots inside each
window (fast ~5 min, slow ~1 h) to answer objective clauses like
``merge:p99<800ms,err<1%`` via the shared
:func:`~semantic_merge_tpu.obs.metrics.histogram_quantile`
interpolation.

Burn-rate semantics (the Google SRE multi-window model): a clause
defines an error budget — ``p99<800ms`` allows 1% of requests over
800 ms, ``err<1%`` allows 1% failures — and the burn rate is the
observed violation fraction divided by that budget. Burn 1.0 = spending
the budget exactly as fast as allowed; burn 10 = ten times too fast.
The engine trips only when **both** windows burn at or above the
threshold (``SEMMERGE_SLO_TRIP``, default 1.0): the fast window makes
the alert responsive, the slow window keeps one latency spike from
paging anyone.

Configuration grammar (``SEMMERGE_SLO`` env or the ``[slo]`` config
table's ``objectives`` key)::

    objective  = target ":" clause ("," clause)*
    objectives = objective (";" objective)*
    target     = "merge" | "diff" | "rebase" | wire verb | "*"
    clause     = "p" NN "<" number ("ms" | "s")    ; latency
               | "err" "<" number "%"              ; error rate

State surfaces as ``slo_burn_rate{objective,window}`` gauges in the
registry (so ``/metrics``, ``SEMMERGE_METRICS`` dumps, and postmortem
bundles all carry it for free), as the ``slo`` block in daemon
``status``, and — via the daemon's monitor thread — as a degraded
``/healthz`` verdict and an ``slo-burn`` flight-recorder bundle on a
sustained trip. Import cost stays stdlib-only (the ``obs`` package
contract); ``observe`` is a few list additions under a lock.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics

#: Objective grammar source (also read by ``config.py``'s ``[slo]``).
ENV_OBJECTIVES = "SEMMERGE_SLO"
#: Fast / slow evaluation windows, seconds.
ENV_FAST_WINDOW = "SEMMERGE_SLO_FAST_WINDOW"
ENV_SLOW_WINDOW = "SEMMERGE_SLO_SLOW_WINDOW"
#: Slot width of the sliding-window ring, seconds.
ENV_SLOT = "SEMMERGE_SLO_SLOT"
#: Monitor-thread evaluation cadence, seconds.
ENV_EVAL_INTERVAL = "SEMMERGE_SLO_EVAL_INTERVAL"
#: Burn-rate threshold at/above which (in both windows) a clause trips.
ENV_TRIP = "SEMMERGE_SLO_TRIP"
#: Opt-in: capture a profile bundle on the first burn trip.
ENV_AUTOPROFILE = "SEMMERGE_SLO_AUTOPROFILE"

DEFAULT_FAST_WINDOW = 300.0
DEFAULT_SLOW_WINDOW = 3600.0
DEFAULT_SLOT = 5.0
DEFAULT_EVAL_INTERVAL = 5.0
DEFAULT_TRIP = 1.0

#: Gauge published per (objective clause, window).
BURN_GAUGE = "slo_burn_rate"
#: Counter of edge-triggered burn trips, by objective clause.
TRIP_COUNTER = "slo_burn_trips_total"

#: CLI-friendly aliases for wire verbs.
VERB_ALIASES = {"merge": "semmerge", "diff": "semdiff",
                "rebase": "semrebase"}
_KNOWN_VERBS = ("semdiff", "semmerge", "semrebase")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class SloParseError(ValueError):
    """Raised for a malformed objective spec — loudly, at daemon
    startup, not silently at 3 a.m. when the alert should have fired."""


class Clause:
    """One parsed clause of an objective: either a latency quantile
    bound (``kind="latency"``: ``quantile`` in (0, 1), ``threshold_s``)
    or an error-rate bound (``kind="error"``). ``budget`` is the
    allowed violation fraction the burn rate divides by."""

    __slots__ = ("target", "kind", "quantile", "threshold_s", "budget",
                 "text")

    def __init__(self, target: str, kind: str, quantile: float,
                 threshold_s: float, budget: float, text: str) -> None:
        self.target = target
        self.kind = kind
        self.quantile = quantile
        self.threshold_s = threshold_s
        self.budget = budget
        self.text = text

    def to_dict(self) -> dict:
        out = {"objective": self.text, "target": self.target,
               "kind": self.kind, "budget": self.budget}
        if self.kind == "latency":
            out["quantile"] = self.quantile
            out["threshold_ms"] = round(self.threshold_s * 1e3, 3)
        return out


def _parse_clause(target: str, raw: str, verb: str) -> Clause:
    body = raw.strip().lower()
    text = f"{target}:{body}"
    if body.startswith("err"):
        rest = body[3:].strip()
        if not rest.startswith("<"):
            raise SloParseError(f"error clause needs '<': {raw!r}")
        pct = rest[1:].strip()
        if not pct.endswith("%"):
            raise SloParseError(f"error clause needs a '%' bound: {raw!r}")
        try:
            budget = float(pct[:-1]) / 100.0
        except ValueError:
            raise SloParseError(f"bad error bound: {raw!r}") from None
        if not 0.0 < budget <= 1.0:
            raise SloParseError(f"error budget out of (0,100]%: {raw!r}")
        return Clause(verb, "error", 0.0, 0.0, budget, text)
    if body.startswith("p"):
        head, sep, bound = body.partition("<")
        if not sep:
            raise SloParseError(f"latency clause needs '<': {raw!r}")
        try:
            q = float(head[1:]) / 100.0
        except ValueError:
            raise SloParseError(f"bad quantile: {raw!r}") from None
        if not 0.0 < q < 1.0:
            raise SloParseError(f"quantile out of (0,100): {raw!r}")
        bound = bound.strip()
        if bound.endswith("ms"):
            scale, bound = 1e-3, bound[:-2]
        elif bound.endswith("s"):
            scale, bound = 1.0, bound[:-1]
        else:
            raise SloParseError(
                f"latency bound needs an 'ms' or 's' unit: {raw!r}")
        try:
            threshold = float(bound) * scale
        except ValueError:
            raise SloParseError(f"bad latency bound: {raw!r}") from None
        if threshold <= 0.0:
            raise SloParseError(f"latency bound must be > 0: {raw!r}")
        # Budget: a pNN bound permits (1 - NN/100) of requests over it.
        return Clause(verb, "latency", q, threshold, 1.0 - q, text)
    raise SloParseError(f"unrecognised clause: {raw!r}")


def parse_objectives(spec: str) -> List[Clause]:
    """Parse an objective spec string into clauses; ``*`` targets
    expand to one clause per known wire verb."""
    clauses: List[Clause] = []
    for objective in str(spec).split(";"):
        objective = objective.strip()
        if not objective:
            continue
        target, sep, rest = objective.partition(":")
        if not sep or not rest.strip():
            raise SloParseError(
                f"objective needs 'target:clause[,clause]': {objective!r}")
        target = target.strip().lower()
        verbs: Sequence[str]
        if target == "*":
            verbs = _KNOWN_VERBS
        else:
            verbs = (VERB_ALIASES.get(target, target),)
        for raw in rest.split(","):
            if not raw.strip():
                continue
            for verb in verbs:
                # A `*` target expands to one labelled clause per verb;
                # a named target keeps the user's spelling in the label.
                label = verb if target == "*" else target
                clauses.append(_parse_clause(label, raw, verb))
    if not clauses:
        raise SloParseError(f"no clauses in spec: {spec!r}")
    return clauses


class _Slot:
    """One time slot of the ring: per-verb bucket counts + errors."""

    __slots__ = ("verbs",)

    def __init__(self) -> None:
        self.verbs: Dict[str, dict] = {}

    def observe(self, verb: str, seconds: float, error: bool,
                n_buckets: int, bucket_index) -> None:
        rec = self.verbs.get(verb)
        if rec is None:
            rec = {"counts": [0] * (n_buckets + 1), "count": 0,
                   "errors": 0}
            self.verbs[verb] = rec
        rec["counts"][bucket_index(seconds)] += 1
        rec["count"] += 1
        if error:
            rec["errors"] += 1


class SloEngine:
    """Slot-ring accounting plus clause evaluation. One instance per
    daemon; ``None`` (no engine) when no objectives are configured, so
    the unconfigured hot path pays nothing."""

    def __init__(self, clauses: Sequence[Clause], *,
                 fast_window: float = DEFAULT_FAST_WINDOW,
                 slow_window: float = DEFAULT_SLOW_WINDOW,
                 slot_seconds: float = DEFAULT_SLOT,
                 trip_threshold: float = DEFAULT_TRIP,
                 buckets: Sequence[float] = metrics.PHASE_BUCKETS,
                 clock=time.monotonic) -> None:
        if not clauses:
            raise ValueError("SloEngine needs at least one clause")
        self.clauses = list(clauses)
        self.fast_window = max(float(fast_window), slot_seconds)
        self.slow_window = max(float(slow_window), self.fast_window)
        self.slot_seconds = max(0.05, float(slot_seconds))
        self.trip_threshold = float(trip_threshold)
        self.buckets = tuple(sorted(buckets))
        self._clock = clock
        self._lock = threading.Lock()
        self._slots: Dict[int, _Slot] = {}
        self._tripped: Dict[str, bool] = {c.text: False for c in clauses}
        from bisect import bisect_left
        self._bisect = bisect_left

    # -- recording ---------------------------------------------------

    def _bucket_index(self, seconds: float) -> int:
        return self._bisect(self.buckets, seconds)

    def observe(self, verb: str, seconds: float,
                error: bool = False) -> None:
        now = self._clock()
        idx = int(now // self.slot_seconds)
        with self._lock:
            slot = self._slots.get(idx)
            if slot is None:
                slot = _Slot()
                self._slots[idx] = slot
                self._evict(idx)
            slot.observe(verb, float(seconds), bool(error),
                         len(self.buckets), self._bucket_index)

    def _evict(self, current_idx: int) -> None:
        horizon = current_idx - int(self.slow_window
                                    // self.slot_seconds) - 1
        for idx in [i for i in self._slots if i < horizon]:
            del self._slots[idx]

    # -- evaluation --------------------------------------------------

    def _window_totals(self, window_s: float) -> Dict[str, dict]:
        """Sum the slots covering the trailing ``window_s`` seconds
        into per-verb aggregates (bucket counts, count, errors)."""
        now = self._clock()
        lo = int((now - window_s) // self.slot_seconds)
        out: Dict[str, dict] = {}
        with self._lock:
            for idx, slot in self._slots.items():
                if idx < lo:
                    continue
                for verb, rec in slot.verbs.items():
                    agg = out.get(verb)
                    if agg is None:
                        agg = {"counts": [0] * (len(self.buckets) + 1),
                               "count": 0, "errors": 0}
                        out[verb] = agg
                    agg["count"] += rec["count"]
                    agg["errors"] += rec["errors"]
                    counts = agg["counts"]
                    for i, c in enumerate(rec["counts"]):
                        counts[i] += c
        return out

    def _fraction_over(self, counts: Sequence[int],
                       threshold_s: float) -> float:
        """Fraction of observations above ``threshold_s``, assuming
        uniform spread inside the bucket that straddles it (the inverse
        of the quantile interpolation, so the two agree)."""
        total = sum(counts)
        if total <= 0:
            return 0.0
        idx = self._bucket_index(threshold_s)
        if idx >= len(self.buckets):
            return counts[-1] / total
        below = sum(counts[:idx])
        inside = counts[idx]
        lower = self.buckets[idx - 1] if idx > 0 else 0.0
        upper = self.buckets[idx]
        frac_in = ((threshold_s - lower) / (upper - lower)
                   if upper > lower else 1.0)
        covered = below + inside * min(1.0, max(0.0, frac_in))
        return max(0.0, 1.0 - covered / total)

    def _clause_burn(self, clause: Clause, totals: Dict[str, dict]
                     ) -> Tuple[float, int]:
        agg = totals.get(clause.target)
        if agg is None or agg["count"] <= 0:
            return 0.0, 0
        if clause.kind == "error":
            violation = agg["errors"] / agg["count"]
        else:
            violation = self._fraction_over(agg["counts"],
                                            clause.threshold_s)
        return violation / clause.budget, agg["count"]

    def evaluate(self, consume_edges: bool = False) -> dict:
        """Compute burn rates for every clause over both windows,
        publish the gauges, and return the status-block payload:
        ``{"healthy", "objectives": [{objective, target, burn_fast,
        burn_slow, tripped, ...}], "windows": {...}}``.

        Trip *edges* (an objective crossing into burning) are latched
        into the returned ``newly_tripped`` list — but only when
        ``consume_edges=True`` (the daemon's monitor thread, which
        fires one postmortem per excursion). Status/healthz reads keep
        the default and never consume an edge, so a poll racing the
        monitor cannot swallow the bundle."""
        fast = self._window_totals(self.fast_window)
        slow = self._window_totals(self.slow_window)
        gauge = metrics.REGISTRY.gauge(
            BURN_GAUGE, "SLO burn rate (violation fraction / budget) "
                        "per objective clause and window")
        rows: List[dict] = []
        newly_tripped: List[dict] = []
        healthy = True
        for clause in self.clauses:
            burn_fast, n_fast = self._clause_burn(clause, fast)
            burn_slow, n_slow = self._clause_burn(clause, slow)
            gauge.set(burn_fast, objective=clause.text, window="fast")
            gauge.set(burn_slow, objective=clause.text, window="slow")
            tripped = (burn_fast >= self.trip_threshold
                       and burn_slow >= self.trip_threshold)
            if tripped:
                healthy = False
            row = dict(clause.to_dict(), burn_fast=round(burn_fast, 4),
                       burn_slow=round(burn_slow, 4),
                       samples_fast=n_fast, samples_slow=n_slow,
                       tripped=tripped)
            rows.append(row)
            if consume_edges:
                was = self._tripped.get(clause.text, False)
                self._tripped[clause.text] = tripped
                if tripped and not was:
                    metrics.REGISTRY.counter(
                        TRIP_COUNTER,
                        "Edge-triggered SLO burn-rate trips, "
                        "by objective").inc(1, objective=clause.text)
                    newly_tripped.append(row)
        return {
            "healthy": healthy,
            "objectives": rows,
            "newly_tripped": newly_tripped,
            "windows": {"fast_s": self.fast_window,
                        "slow_s": self.slow_window,
                        "slot_s": self.slot_seconds,
                        "trip_threshold": self.trip_threshold},
        }

    def status(self) -> dict:
        """The ``slo`` block for daemon ``status`` — a non-consuming
        :meth:`evaluate` verdict plus live window quantiles per verb."""
        verdict = self.evaluate()
        verdict.pop("newly_tripped", None)
        fast = self._window_totals(self.fast_window)
        verdict["window_quantiles"] = {
            verb: {
                "p50_ms": round(metrics.histogram_quantile(
                    self.buckets, agg["counts"], 0.50) * 1e3, 3),
                "p99_ms": round(metrics.histogram_quantile(
                    self.buckets, agg["counts"], 0.99) * 1e3, 3),
                "count": agg["count"],
                "errors": agg["errors"],
            }
            for verb, agg in sorted(fast.items())
        }
        return verdict

    def window_snapshot(self, window: str = "fast") -> Dict[str, dict]:
        """Per-verb aggregates for one window — the live-daemon source
        for ``semmerge perf record --daemon``."""
        window_s = (self.fast_window if window == "fast"
                    else self.slow_window)
        return self._window_totals(window_s)


def from_env(config_objectives: Optional[str] = None, *,
             config_fast_window: Optional[float] = None,
             config_slow_window: Optional[float] = None,
             clock=time.monotonic) -> Optional[SloEngine]:
    """Build the engine from ``SEMMERGE_SLO`` (env wins) or the
    ``[slo]`` config table's objective string; ``None`` when neither
    is set. Window env knobs override the config values."""
    spec = os.environ.get(ENV_OBJECTIVES, "").strip() \
        or (config_objectives or "").strip()
    if not spec:
        return None
    clauses = parse_objectives(spec)
    return SloEngine(
        clauses,
        fast_window=_env_float(
            ENV_FAST_WINDOW, config_fast_window or DEFAULT_FAST_WINDOW),
        slow_window=_env_float(
            ENV_SLOW_WINDOW, config_slow_window or DEFAULT_SLOW_WINDOW),
        slot_seconds=_env_float(ENV_SLOT, DEFAULT_SLOT),
        trip_threshold=_env_float(ENV_TRIP, DEFAULT_TRIP),
        clock=clock,
    )
