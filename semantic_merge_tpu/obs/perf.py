"""Perf-regression sentinel — normalize bench snapshots, keep a
machine-readable trajectory, compare against a committed baseline.

The repo accumulates ``BENCH_*.json`` one-line records (one per bench
preset run) but until now nothing *compared* them across commits: a
10% host-tail regression would land silently. This module is the
shared core behind ``semmerge perf record|compare`` and the standalone
``scripts/perf_gate.py`` CI gate:

- :func:`normalize_record` reduces a bench record (or a live daemon
  window snapshot) to the comparable surface: headline ``value`` +
  ``unit``, the ``phases_ms`` split, and the metric description;
- ``PERF_BASELINE.json`` (:func:`load_baseline`/:func:`save_baseline`)
  maps snapshot keys (``r05``, ``tpu_rung5``, ``daemon`` …) to
  normalized entries;
- :func:`compare_entry` applies unit-aware direction (``*/sec`` is
  higher-better; ``ms``/``s``/``pct`` lower-better; phase walls always
  lower-better) with separate headline and per-phase tolerance bands;
- :func:`append_trajectory` appends every bench emission to
  ``BENCH_trajectory.jsonl`` (override: ``SEMMERGE_BENCH_TRAJECTORY``)
  so the perf history is a greppable, plottable artifact instead of a
  pile of mutable snapshot files.

Stdlib-only, like the rest of :mod:`semantic_merge_tpu.obs`.
"""
from __future__ import annotations

import json
import os
import pathlib
import time
from typing import Dict, List, Optional, Tuple

BASELINE_SCHEMA = 1
#: Default artifact names, resolved against the repo root by callers.
BASELINE_NAME = "PERF_BASELINE.json"
TRAJECTORY_NAME = "BENCH_trajectory.jsonl"
ENV_TRAJECTORY = "SEMMERGE_BENCH_TRAJECTORY"

DEFAULT_TOLERANCE_PCT = 10.0
DEFAULT_PHASE_TOLERANCE_PCT = 25.0
#: Phases faster than this in the baseline are noise, not signal.
MIN_PHASE_MS = 5.0

#: Units where a larger number is better.
_HIGHER_BETTER_SUFFIXES = ("/sec", "/s")

#: Bench-record fields (beyond the headline ``value``) carried into
#: baseline entries and compared with the headline tolerance. The
#: direction is explicit because these are unitless ratios, not
#: suffix-typed rates: the batchserve chips axis must not silently
#: lose mesh scaling efficiency or per-chip throughput.
GUARDED_FIELDS = {
    "scaling_efficiency": "higher",
    "merges_per_sec_per_chip": "higher",
    # Fleet preset: the single-member floor and the 3-member headline
    # must not regress, and the rendezvous rehash quality (fraction of
    # keys that move owners on a single-member loss; lower-better) must
    # stay near 1/N rather than drifting toward a mod-N ring's ~1.0.
    "fleet_merges_per_sec_m1": "higher",
    "fleet_merges_per_sec_m3": "higher",
    "fleet_rehash_miss_rate": "lower",
    # Fleetwan preset (cross-host fleet over TCP with injected dial
    # latency): the post-churn rehash miss rate — cold dispatches after
    # one elastic join + one drain, with the incremental handoff
    # prewarming moved keys — must stay under the 0.15 gate instead of
    # drifting back toward the unassisted ~1/N rendezvous rehash.
    "fleetwan_rehash_miss_rate": "lower",
    # Tracecost preset, fleet leg: what the stitched observability
    # plane (member span shipping + router grafting + artifact/OTLP
    # sealing) costs a routed merge, as a percent of the dark fleet's
    # median latency. The baseline entry anchors this at the 2%
    # budget rather than a (noise-floor) measurement, so the guard
    # trips exactly when the budget does.
    "fleet_trace_overhead_pct": "lower",
    # Telcost preset (PR-20): the full per-request telemetry pipeline
    # (sampling verdict + window rollup + anomaly observation + trace
    # store write) as a percent of dark merge latency. Like the fleet
    # trace leg, the baseline anchors the documented 2% budget so the
    # guard trips exactly when the budget does.
    "telemetry_overhead_pct": "lower",
    # Devtail preset (PR-18): the post-kernel host tail
    # (compose_materialize + serialize, disjoint accounting) must not
    # creep back up once the device-render path owns serialization, and
    # the repeat-base leg's residency hit rate must stay warm — a cold
    # cache means scan_encode+h2d are back on the critical path.
    "host_tail_ms": "lower",
    "residency_hit_rate": "higher",
}


def higher_is_better(unit: str) -> bool:
    return str(unit).endswith(_HIGHER_BETTER_SUFFIXES)


def record_key(path: pathlib.Path | str) -> str:
    """Baseline key for a snapshot file: the stem minus the ``BENCH_``
    prefix (``BENCH_r05.json`` → ``r05``)."""
    stem = pathlib.Path(path).stem
    return stem[6:] if stem.startswith("BENCH_") else stem


def normalize_record(record: dict, *, source: Optional[str] = None
                     ) -> dict:
    """Reduce one bench record to the comparable entry shape."""
    entry = {
        "metric": str(record.get("metric", "")),
        "value": float(record.get("value", 0.0)),
        "unit": str(record.get("unit", "")),
        "recorded_at": round(time.time(), 3),
    }
    phases = record.get("phases_ms")
    if isinstance(phases, dict) and phases:
        entry["phases_ms"] = {str(k): float(v)
                              for k, v in sorted(phases.items())}
    guarded = {name: float(record[name]) for name in sorted(GUARDED_FIELDS)
               if isinstance(record.get(name), (int, float))}
    if guarded:
        entry["guarded"] = guarded
    if record.get("error"):
        entry["error"] = str(record["error"])
    if source:
        entry["source"] = str(source)
    return entry


def daemon_entry(status: dict) -> dict:
    """Normalize a live daemon ``status`` payload into a baseline
    entry: overall request p99 as the headline (lower-better), per-verb
    p50/p99 as the phase split. Prefers the SLO engine's sliding-window
    quantiles when present (current traffic), falling back to the
    cumulative ``service_request_seconds`` histogram."""
    phases: Dict[str, float] = {}
    worst_p99 = 0.0
    slo = status.get("slo") or {}
    quantiles = slo.get("window_quantiles") or {}
    if quantiles:
        for verb, row in quantiles.items():
            phases[f"{verb}_p50"] = float(row.get("p50_ms", 0.0))
            phases[f"{verb}_p99"] = float(row.get("p99_ms", 0.0))
            worst_p99 = max(worst_p99, float(row.get("p99_ms", 0.0)))
        source = "slo-window"
    else:
        from . import metrics as obs_metrics
        hists = (status.get("metrics") or {}).get("histograms") or {}
        hist = hists.get("service_request_seconds") or {}
        buckets = hist.get("buckets") or list(obs_metrics.PHASE_BUCKETS)
        for series in hist.get("series", ()):
            verb = series.get("labels", {}).get("verb", "?")
            counts = series.get("counts", ())
            p50 = obs_metrics.histogram_quantile(buckets, counts, 0.50)
            p99 = obs_metrics.histogram_quantile(buckets, counts, 0.99)
            phases[f"{verb}_p50"] = round(p50 * 1e3, 3)
            phases[f"{verb}_p99"] = round(p99 * 1e3, 3)
            worst_p99 = max(worst_p99, p99 * 1e3)
        source = "cumulative-histogram"
    return normalize_record({
        "metric": "live daemon per-verb request latency (worst p99)",
        "value": round(worst_p99, 3),
        "unit": "ms",
        "phases_ms": phases,
    }, source=source)


# ---------------------------------------------------------------------------
# Baseline IO

def load_baseline(path: pathlib.Path | str) -> dict:
    data = json.loads(pathlib.Path(path).read_text(encoding="utf-8"))
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(f"{path}: not a perf baseline (no 'entries')")
    return data


def save_baseline(path: pathlib.Path | str, entries: Dict[str, dict]
                  ) -> None:
    payload = {"schema": BASELINE_SCHEMA,
               "entries": {k: entries[k] for k in sorted(entries)}}
    pathlib.Path(path).write_text(
        json.dumps(payload, indent=2, sort_keys=True) + "\n",
        encoding="utf-8")


# ---------------------------------------------------------------------------
# Comparison

def _delta_pct(current: float, baseline: float) -> float:
    if baseline == 0.0:
        return 0.0
    return (current - baseline) / abs(baseline) * 100.0


def compare_entry(key: str, current: dict, baseline: dict, *,
                  tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
                  phase_tolerance_pct: float = DEFAULT_PHASE_TOLERANCE_PCT,
                  min_phase_ms: float = MIN_PHASE_MS) -> List[dict]:
    """Compare one normalized entry against its baseline entry.
    Returns one finding per compared field; a finding with
    ``regression=True`` means the field moved past its tolerance in
    the bad direction."""
    findings: List[dict] = []
    cur_v = float(current.get("value", 0.0))
    base_v = float(baseline.get("value", 0.0))
    unit = str(baseline.get("unit", current.get("unit", "")))
    delta = _delta_pct(cur_v, base_v)
    bad = -delta if higher_is_better(unit) else delta
    findings.append({
        "key": key, "field": "value", "unit": unit,
        "current": cur_v, "baseline": base_v,
        "delta_pct": round(delta, 2),
        "tolerance_pct": tolerance_pct,
        "regression": bad > tolerance_pct,
    })
    base_guarded = baseline.get("guarded") or {}
    cur_guarded = current.get("guarded") or {}
    for name in sorted(set(base_guarded) & set(cur_guarded)):
        bg, cg = float(base_guarded[name]), float(cur_guarded[name])
        gdelta = _delta_pct(cg, bg)
        gbad = -gdelta if GUARDED_FIELDS.get(name) == "higher" else gdelta
        findings.append({
            "key": key, "field": f"guarded.{name}", "unit": "ratio",
            "current": cg, "baseline": bg,
            "delta_pct": round(gdelta, 2),
            "tolerance_pct": tolerance_pct,
            "regression": gbad > tolerance_pct,
        })
    base_phases = baseline.get("phases_ms") or {}
    cur_phases = current.get("phases_ms") or {}
    for phase in sorted(set(base_phases) & set(cur_phases)):
        bp, cp = float(base_phases[phase]), float(cur_phases[phase])
        if bp < min_phase_ms:
            continue
        pdelta = _delta_pct(cp, bp)
        findings.append({
            "key": key, "field": f"phases_ms.{phase}", "unit": "ms",
            "current": cp, "baseline": bp,
            "delta_pct": round(pdelta, 2),
            "tolerance_pct": phase_tolerance_pct,
            "regression": pdelta > phase_tolerance_pct,
        })
    return findings


def compare_many(entries: Dict[str, dict], baseline: dict, *,
                 tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
                 phase_tolerance_pct: float = DEFAULT_PHASE_TOLERANCE_PCT
                 ) -> Tuple[bool, List[dict]]:
    """Compare every entry that has a baseline counterpart. Returns
    ``(ok, findings)``; entries missing from the baseline produce a
    non-regression ``missing-baseline`` finding (new presets must not
    fail the gate)."""
    findings: List[dict] = []
    base_entries = baseline.get("entries", {})
    for key in sorted(entries):
        if key not in base_entries:
            findings.append({"key": key, "field": "value",
                             "regression": False,
                             "note": "missing-baseline"})
            continue
        findings.extend(compare_entry(
            key, entries[key], base_entries[key],
            tolerance_pct=tolerance_pct,
            phase_tolerance_pct=phase_tolerance_pct))
    ok = not any(f["regression"] for f in findings)
    return ok, findings


def format_findings(findings: List[dict]) -> str:
    lines = []
    for f in findings:
        if f.get("note") == "missing-baseline":
            lines.append(f"  new   {f['key']}: no baseline entry "
                         f"(record one with 'semmerge perf record')")
            continue
        mark = "REGRESSION" if f["regression"] else "ok"
        lines.append(
            f"  {mark:10s} {f['key']}.{f['field']}: "
            f"{f['current']:g} vs {f['baseline']:g} {f.get('unit', '')} "
            f"({f['delta_pct']:+.1f}%, tol {f['tolerance_pct']:g}%)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Trajectory

def trajectory_path(root: pathlib.Path | str = ".") -> pathlib.Path:
    override = os.environ.get(ENV_TRAJECTORY, "").strip()
    if override:
        return pathlib.Path(override)
    return pathlib.Path(root) / TRAJECTORY_NAME


def append_trajectory(record: dict, *, preset: Optional[str] = None,
                      root: pathlib.Path | str = ".") -> Optional[pathlib.Path]:
    """Append one bench record to the trajectory file; returns the
    path, or ``None`` on write failure (the trajectory is a courtesy —
    it must never fail a bench run)."""
    row = dict(record)
    row.setdefault("ts", round(time.time(), 3))
    if preset:
        row.setdefault("preset", preset)
    try:
        path = trajectory_path(root)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(row, sort_keys=True) + "\n")
        return path
    except OSError:
        return None
