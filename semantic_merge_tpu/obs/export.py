"""OTLP export — the standard-wire-format edge of
:mod:`semantic_merge_tpu.obs`.

Maps the internal observability artifacts (span-dict trees from
:mod:`.spans`, the :meth:`~semantic_merge_tpu.obs.metrics.Registry.to_dict`
registry form) onto OTLP JSON (``opentelemetry-proto`` JSON encoding),
and ships them to a collector over plain HTTP — so Jaeger/Tempo/
Prometheus-class backends ingest fleet traces without bespoke glue.

Off by default: everything here is inert until
``SEMMERGE_OTLP_ENDPOINT`` names a collector base URL (the exporter
POSTs to ``<endpoint>/v1/traces`` and ``<endpoint>/v1/metrics``).
Export is fire-and-forget through a bounded queue drained by one
background thread; when the queue is full the payload is *dropped* and
counted (``otlp_dropped_total``) — telemetry never applies backpressure
to the merge path, per the flight-recorder discipline. Delivery
outcomes land in ``otlp_exported_total{kind}`` /
``otlp_errors_total``; stdlib-only (``urllib``), no SDK dependency.

The payload shape is enforced by ``validate_export`` in
``scripts/check_trace_schema.py``; schema notes live in the runbook's
Observability chapter.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from . import metrics as obs_metrics

ENV_ENDPOINT = "SEMMERGE_OTLP_ENDPOINT"
ENV_QUEUE = "SEMMERGE_OTLP_QUEUE"
ENV_TIMEOUT = "SEMMERGE_OTLP_TIMEOUT"

DEFAULT_QUEUE = 256
DEFAULT_TIMEOUT_S = 3.0

#: OTLP span status codes (``opentelemetry-proto`` Status.StatusCode).
_STATUS_OK = 1
_STATUS_ERROR = 2


def _hex_trace_id(trace_id: str) -> str:
    """Internal trace ids are 16 hex chars (``os.urandom(8).hex()``);
    OTLP wants exactly 32. Left-pad rather than re-mint so the exported
    id stays greppable against our artifacts."""
    tid = "".join(c for c in str(trace_id) if c in "0123456789abcdef")
    return (tid or "0").rjust(32, "0")[-32:]


def _hex_span_id(span_id: int) -> str:
    return format(int(span_id) & ((1 << 64) - 1), "016x")


def _attr_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _attrs(pairs: Dict[str, Any]) -> List[dict]:
    return [{"key": k, "value": _attr_value(v)}
            for k, v in pairs.items() if v is not None]


def spans_to_otlp(trace_id: str, span_rows: List[dict], *,
                  service_name: str = "semmerge",
                  epoch_unix_nano: Optional[int] = None) -> dict:
    """OTLP ``ExportTraceServiceRequest`` (JSON form) for one trace.

    ``span_rows`` is the plain-dict form of
    :meth:`~semantic_merge_tpu.obs.spans.SpanRecorder.span_dicts` —
    ``t_start`` offsets relative to a recorder epoch. OTLP wants
    absolute unix nanos, so the tree is anchored at ``epoch_unix_nano``
    (defaulting to "the latest span ended just now", the only anchor a
    monotonic-clock recorder can offer after the fact)."""
    if epoch_unix_nano is None:
        t_max = max((float(r.get("t_start", 0.0)) +
                     float(r.get("seconds", 0.0)) for r in span_rows),
                    default=0.0)
        epoch_unix_nano = time.time_ns() - int(t_max * 1e9)
    tid = _hex_trace_id(trace_id)
    spans = []
    for row in span_rows:
        start = epoch_unix_nano + int(float(row.get("t_start", 0.0)) * 1e9)
        end = start + int(float(row.get("seconds", 0.0)) * 1e9)
        attrs = _attrs({"layer": row.get("layer"),
                        "thread": row.get("thread")})
        attrs += _attrs(dict(row.get("meta") or {}))
        status: Dict[str, Any] = {"code": _STATUS_OK}
        if row.get("status") == "error":
            status = {"code": _STATUS_ERROR,
                      "message": str(row.get("error") or "")}
        span = {
            "traceId": tid,
            "spanId": _hex_span_id(row.get("span_id", 0)),
            "name": str(row.get("name", "")),
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start),
            "endTimeUnixNano": str(end),
            "attributes": attrs,
            "status": status,
        }
        parent = row.get("parent_id", -1)
        if isinstance(parent, int) and parent >= 0:
            span["parentSpanId"] = _hex_span_id(parent)
        spans.append(span)
    return {
        "resourceSpans": [{
            "resource": {"attributes": _attrs(
                {"service.name": service_name, "process.pid": os.getpid()})},
            "scopeSpans": [{
                "scope": {"name": "semantic_merge_tpu"},
                "spans": spans,
            }],
        }],
    }


def _metric_points(series: List[dict], now_ns: int) -> List[dict]:
    return [{"attributes": _attrs(s.get("labels") or {}),
             "timeUnixNano": str(now_ns),
             "asDouble": float(s.get("value", 0.0))} for s in series]


def metrics_to_otlp(registry_dict: dict, *,
                    service_name: str = "semmerge",
                    time_unix_nano: Optional[int] = None) -> dict:
    """OTLP ``ExportMetricsServiceRequest`` (JSON form) of a
    :meth:`~semantic_merge_tpu.obs.metrics.Registry.to_dict` payload.
    Counters become cumulative monotonic sums, gauges gauges, histograms
    explicit-bucket histograms (our per-bucket counts map 1:1 onto OTLP
    ``bucketCounts``; per-bucket exemplars ride along)."""
    now_ns = time.time_ns() if time_unix_nano is None else time_unix_nano
    out_metrics: List[dict] = []
    for name in sorted(registry_dict.get("counters", ())):
        m = registry_dict["counters"][name]
        out_metrics.append({
            "name": name, "description": m.get("help", ""),
            "sum": {"aggregationTemporality": 2, "isMonotonic": True,
                    "dataPoints": _metric_points(m.get("series", []), now_ns)},
        })
    for name in sorted(registry_dict.get("gauges", ())):
        m = registry_dict["gauges"][name]
        out_metrics.append({
            "name": name, "description": m.get("help", ""),
            "gauge": {"dataPoints": _metric_points(m.get("series", []),
                                                   now_ns)},
        })
    for name in sorted(registry_dict.get("histograms", ())):
        m = registry_dict["histograms"][name]
        bounds = [float(b) for b in m.get("buckets", [])]
        points = []
        for s in m.get("series", []):
            exemplars = [{"traceId": _hex_trace_id(e.get("trace_id", "")),
                          "timeUnixNano": str(now_ns),
                          "asDouble": float(e.get("value", 0.0))}
                         for _, e in sorted((s.get("exemplars") or {}).items())]
            points.append({
                "attributes": _attrs(s.get("labels") or {}),
                "timeUnixNano": str(now_ns),
                "count": str(int(s.get("count", 0))),
                "sum": float(s.get("sum", 0.0)),
                "bucketCounts": [str(int(c)) for c in s.get("counts", [])],
                "explicitBounds": bounds,
                "exemplars": exemplars,
            })
        out_metrics.append({
            "name": name, "description": m.get("help", ""),
            "histogram": {"aggregationTemporality": 2,
                          "dataPoints": points},
        })
    return {
        "resourceMetrics": [{
            "resource": {"attributes": _attrs(
                {"service.name": service_name, "process.pid": os.getpid()})},
            "scopeMetrics": [{
                "scope": {"name": "semantic_merge_tpu"},
                "metrics": out_metrics,
            }],
        }],
    }


class Exporter:
    """Bounded-queue background OTLP shipper.

    ``enqueue`` never blocks and never raises toward the merge path: a
    full queue drops the payload and bumps ``otlp_dropped_total{kind}``.
    One daemon thread drains the queue, POSTing JSON to
    ``<endpoint>/v1/traces`` / ``<endpoint>/v1/metrics``; delivery
    failures bump ``otlp_errors_total`` (the payload is not retried —
    a collector outage must not grow unbounded state here)."""

    def __init__(self, endpoint: str, *, queue_size: int = DEFAULT_QUEUE,
                 timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = timeout_s
        self._q: "queue.Queue[Optional[Tuple[str, dict]]]" = \
            queue.Queue(maxsize=max(1, queue_size))
        self._exported = obs_metrics.REGISTRY.counter(
            "otlp_exported_total", "OTLP payloads delivered, by kind.")
        self._dropped = obs_metrics.REGISTRY.counter(
            "otlp_dropped_total",
            "OTLP payloads dropped on a full export queue, by kind.")
        self._errors = obs_metrics.REGISTRY.counter(
            "otlp_errors_total", "OTLP delivery failures.")
        self._thread = threading.Thread(
            target=self._run, name="otlp-exporter", daemon=True)
        self._thread.start()

    def export_trace(self, trace_id: str, span_rows: List[dict],
                     **kwargs: Any) -> None:
        self._enqueue("traces", spans_to_otlp(trace_id, span_rows, **kwargs))

    def export_metrics(self, registry_dict: dict, **kwargs: Any) -> None:
        self._enqueue("metrics", metrics_to_otlp(registry_dict, **kwargs))

    def _enqueue(self, kind: str, payload: dict) -> None:
        try:
            self._q.put_nowait((kind, payload))
        except queue.Full:
            self._dropped.inc(kind=kind)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            kind, payload = item
            try:
                self._post(kind, payload)
                self._exported.inc(kind=kind)
            except Exception:
                self._errors.inc()

    def _post(self, kind: str, payload: dict) -> None:
        req = urllib.request.Request(
            f"{self.endpoint}/v1/{kind}",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the worker after the queue drains (best-effort)."""
        try:
            self._q.put_nowait(None)
        except queue.Full:
            # A full queue must not wedge shutdown behind a dead
            # collector: drop one payload to make room for the sentinel.
            try:
                self._q.get_nowait()
                self._q.put_nowait(None)
            except (queue.Empty, queue.Full):
                pass
        self._thread.join(timeout=timeout_s)


_singleton_lock = threading.Lock()
_singleton: Optional[Exporter] = None
_singleton_endpoint: Optional[str] = None


def maybe_exporter() -> Optional[Exporter]:
    """The process-wide :class:`Exporter`, or ``None`` when
    ``SEMMERGE_OTLP_ENDPOINT`` is unset — callers gate on the return so
    export stays zero-cost when off."""
    global _singleton, _singleton_endpoint
    endpoint = os.environ.get(ENV_ENDPOINT, "").strip()
    if not endpoint:
        return None
    with _singleton_lock:
        if _singleton is None or _singleton_endpoint != endpoint:
            try:
                qsize = int(os.environ.get(ENV_QUEUE, "") or DEFAULT_QUEUE)
            except ValueError:
                qsize = DEFAULT_QUEUE
            try:
                timeout_s = float(os.environ.get(ENV_TIMEOUT, "")
                                  or DEFAULT_TIMEOUT_S)
            except ValueError:
                timeout_s = DEFAULT_TIMEOUT_S
            _singleton = Exporter(endpoint, queue_size=qsize,
                                  timeout_s=timeout_s)
            _singleton_endpoint = endpoint
        return _singleton
