"""Structured spans and events — the timing layer of
:mod:`semantic_merge_tpu.obs`.

A :class:`SpanRecorder` collects nestable, thread-safe span records
(monotonic wall-time, depth/parent links, ok/error status, free-form
meta) and point events. Recorders resolve in two scopes:

- **Request scope** (:func:`request_scope`): a
  :class:`contextvars.ContextVar` the merge service daemon sets around
  each request, carrying that request's recorder *and* its
  ``trace_id``. Concurrent daemon requests record into disjoint
  recorders; :func:`trace_id` exposes the id to any layer (worker
  frames, postmortem bundles, client-visible errors).
- **Global activation** (:func:`activate`): the pre-daemon
  compatibility layer — the CLI ``Tracer`` activates one recorder for
  ``--trace``/``--profile`` runs, ``bench.py`` activates one around
  its instrumented merge, the daemon's ``--events`` recorder catches
  everything outside request scopes. Inside a request scope,
  ``activate`` rebinds the *scope's* recorder instead (so a ``--trace``
  run executed by the daemon stays request-local).

Three always-on guarantees keep instrumentation writable in hot paths:

- :func:`span` and :func:`record` feed the phase histogram of
  :mod:`semantic_merge_tpu.obs.metrics` unconditionally (a dict update),
  so cumulative per-phase timing exists even without a recorder;
- the same call sites feed the bounded flight-recorder ring of
  :mod:`semantic_merge_tpu.obs.flight` (one dict append), so a fault in
  an uninstrumented run still leaves span-level evidence;
- full span records (nesting, meta, JSONL emission) are built only
  while a recorder is active, so dark runs pay two ``perf_counter``
  calls per span, a histogram update, and a ring append — nothing else.

Code that needs *expensive* timing fences (``block_until_ready`` on
device buffers) gates them on :func:`detailed_active` — detailed device
phase splits exist exactly when someone asked for them (``--trace``,
bench instrumentation), never for the daemon's always-on per-request
recorders.

Artifacts: the recorder serializes to JSONL rows (``.semmerge-events.jsonl``,
written by ``Tracer.write``) and to the ``spans`` array summarized into
``.semmerge-trace.json``. Schemas are documented in ``runbook.md`` and
enforced by ``scripts/check_trace_schema.py``.
"""
from __future__ import annotations

import contextlib
import json
import pathlib
import threading
import time
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from . import flight, metrics

#: Default events artifact name (next to ``.semmerge-trace.json``).
EVENTS_ARTIFACT = ".semmerge-events.jsonl"

_state_lock = threading.Lock()
_active: "Optional[SpanRecorder]" = None
_tls = threading.local()


class _Scope:
    """One request's tracing scope: its recorder (may be rebound by a
    request-local ``Tracer``) and its wire-visible ``trace_id``."""

    __slots__ = ("recorder", "trace_id")

    def __init__(self, recorder: "Optional[SpanRecorder]",
                 trace_id: Optional[str]) -> None:
        self.recorder = recorder
        self.trace_id = trace_id


_SCOPE: "ContextVar[Optional[_Scope]]" = ContextVar(
    "semmerge_span_scope", default=None)


@dataclass(slots=True)
class SpanRecord:
    """One completed span. ``t_start`` is seconds since the recorder's
    epoch (monotonic clock); ``parent_id`` is ``-1`` for roots."""

    name: str
    layer: Optional[str]
    t_start: float
    seconds: float
    depth: int
    span_id: int
    parent_id: int
    thread: str
    status: str  # "ok" | "error"
    error: Optional[str]
    meta: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "layer": self.layer,
            "t_start": round(self.t_start, 6),
            "seconds": round(self.seconds, 6),
            "depth": self.depth,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "thread": self.thread,
            "status": self.status,
            "error": self.error,
            "meta": self.meta,
        }


class SpanRecorder:
    """Thread-safe sink for spans and events of one observed run.

    ``detailed`` opts the run into *expensive* timing splits (device
    sync fences in the fused engine). Explicitly requested recorders
    (``--trace``, bench instrumentation) default to detailed; the
    daemon's always-on per-request recorders pass ``detailed=False`` so
    request tracing never serializes the dispatch/fetch overlap."""

    def __init__(self, detailed: bool = True) -> None:
        self._lock = threading.Lock()
        self._next_id = 0
        self.epoch = time.perf_counter()
        self.detailed = detailed
        self.spans: List[SpanRecord] = []
        self.events: List[dict] = []

    def _new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _add_span(self, rec: SpanRecord) -> None:
        with self._lock:
            self.spans.append(rec)

    def add_event(self, name: str, fields: Dict[str, Any]) -> None:
        row = {"name": name, "t_start": round(time.perf_counter() - self.epoch, 6),
               "thread": threading.current_thread().name, "fields": fields}
        with self._lock:
            self.events.append(row)

    def absorb(self, other: "SpanRecorder", **extra_meta: Any) -> None:
        """Graft another recorder's rows into this one: span starts are
        re-based onto this recorder's epoch, ids are remapped (parent
        links preserved within the absorbed set), and ``extra_meta``
        (typically ``trace_id=...``) is stamped on every span. The
        daemon's ``--events`` recorder absorbs each finished request's
        scoped recorder so the daemon-lifetime artifact still covers
        every request."""
        shift = other.epoch - self.epoch
        with other._lock:
            spans = list(other.spans)
            events = list(other.events)
        id_map = {s.span_id: self._new_id() for s in spans}
        with self._lock:
            for s in spans:
                self.spans.append(SpanRecord(
                    name=s.name, layer=s.layer,
                    t_start=s.t_start + shift, seconds=s.seconds,
                    depth=s.depth, span_id=id_map[s.span_id],
                    parent_id=id_map.get(s.parent_id, -1),
                    thread=s.thread, status=s.status, error=s.error,
                    meta=dict(s.meta, **extra_meta)))
            for e in events:
                self.events.append(
                    dict(e, t_start=round(e["t_start"] + shift, 6)))

    def absorb_dicts(self, rows: List[dict], *, t_base: float = 0.0,
                     parent_id: int = -1, depth: int = 0,
                     **extra_meta: Any) -> None:
        """Graft plain span dicts (the wire form of :meth:`span_dicts`)
        into this recorder — the cross-process half of :meth:`absorb`.
        ``perf_counter`` epochs are not comparable between processes, so
        the caller anchors the grafted subtree at ``t_base`` (seconds
        relative to *this* recorder's epoch — typically the start of the
        relay span that carried the rows). Ids are remapped with parent
        links preserved inside the absorbed set; absorbed roots are
        re-parented under ``parent_id`` with their depth shifted by
        ``depth``; ``extra_meta`` (typically ``member=...`` /
        ``attempt=...``) is stamped on every span. ``seconds`` is
        carried through untouched so the grafted subtree's phase totals
        equal the shipped tree's byte-for-byte."""
        keyed = [(row, self._new_id()) for row in rows]
        id_map = {row["span_id"]: new_id for row, new_id in keyed
                  if isinstance(row.get("span_id"), int)}
        with self._lock:
            for row, new_id in keyed:
                old_parent = row.get("parent_id", -1)
                self.spans.append(SpanRecord(
                    name=str(row.get("name", "")),
                    layer=row.get("layer"),
                    t_start=float(row.get("t_start", 0.0)) + t_base,
                    seconds=float(row.get("seconds", 0.0)),
                    depth=int(row.get("depth", 0)) + depth,
                    span_id=id_map.get(row.get("span_id"), new_id),
                    parent_id=id_map.get(old_parent, parent_id),
                    thread=str(row.get("thread", "")),
                    status=str(row.get("status", "ok")),
                    error=row.get("error"),
                    meta=dict(row.get("meta") or {}, **extra_meta)))

    # -- views ------------------------------------------------------------

    def span_dicts(self) -> List[dict]:
        with self._lock:
            return [s.to_dict() for s in
                    sorted(self.spans, key=lambda s: s.t_start)]

    def phase_totals(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        with self._lock:
            for s in self.spans:
                out[s.name] = out.get(s.name, 0.0) + s.seconds
        return out

    def layers(self) -> set:
        with self._lock:
            return {s.layer for s in self.spans if s.layer}

    def event_rows(self) -> List[dict]:
        """Every record as one JSONL-able row, time-ordered: spans carry
        ``type: "span"``, point events ``type: "event"``."""
        rows = [dict(s.to_dict(), type="span") for s in self.spans]
        with self._lock:
            rows += [dict(e, type="event") for e in self.events]
        rows.sort(key=lambda r: r["t_start"])
        return rows

    def write_jsonl(self, path: pathlib.Path | str) -> None:
        lines = [json.dumps(row, default=str) for row in self.event_rows()]
        pathlib.Path(path).write_text("\n".join(lines) + ("\n" if lines else ""),
                                      encoding="utf-8")


# ---------------------------------------------------------------------------
# Scope resolution: request-scoped recorder first, then the global one.

def current() -> Optional[SpanRecorder]:
    scope = _SCOPE.get()
    if scope is not None and scope.recorder is not None:
        return scope.recorder
    return _active


def active() -> bool:
    """True when a recorder is collecting full span records."""
    return current() is not None


def detailed_active() -> bool:
    """True when a *detailed* recorder is collecting — the gate for
    timing work with side effects (device sync fences,
    ``jax.live_arrays`` walks). The daemon's always-on per-request
    recorders are not detailed; ``--trace``/bench recorders are."""
    rec = current()
    return rec is not None and rec.detailed


def trace_id() -> Optional[str]:
    """The current request's ``trace_id``, or ``None`` outside any
    request scope (one-shot CLI runs, daemon-internal threads)."""
    scope = _SCOPE.get()
    return scope.trace_id if scope is not None else None


@contextlib.contextmanager
def request_scope(trace_id: Optional[str],
                  recorder: "Optional[SpanRecorder]" = None
                  ) -> Iterator[_Scope]:
    """Scope a per-request recorder + trace id over the current
    thread/context (the daemon sets one around each request; contextvar
    semantics follow ``utils.reqenv.overlay``). While a scope is set,
    :func:`activate`/:func:`deactivate` rebind the scope's recorder
    instead of the process-global one."""
    scope = _Scope(recorder, trace_id)
    token = _SCOPE.set(scope)
    try:
        yield scope
    finally:
        _SCOPE.reset(token)


def activate(recorder: SpanRecorder) -> None:
    scope = _SCOPE.get()
    if scope is not None:
        scope.recorder = recorder
        return
    global _active
    with _state_lock:
        _active = recorder


def deactivate(recorder: Optional[SpanRecorder] = None) -> None:
    """Deactivate ``recorder`` (or whatever is active). A stale handle —
    some other recorder has since been activated — is a no-op, so
    overlapping Tracer lifetimes cannot clobber each other."""
    scope = _SCOPE.get()
    if scope is not None:
        if recorder is None or scope.recorder is recorder:
            scope.recorder = None
        return
    global _active
    with _state_lock:
        if recorder is None or _active is recorder:
            _active = None


@contextlib.contextmanager
def activated(recorder: SpanRecorder):
    activate(recorder)
    try:
        yield recorder
    finally:
        deactivate(recorder)


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


# ---------------------------------------------------------------------------
# Recording API

@contextlib.contextmanager
def span(name: str, layer: Optional[str] = None, **meta: Any):
    """Time a block. Always feeds the phase histogram and the flight
    ring; records a full :class:`SpanRecord` (with nesting links) when
    a recorder is active. Exceptions propagate and mark the span
    ``status="error"``."""
    rec = current()
    frame = None
    if rec is not None:
        stack = _stack()
        parent_id = stack[-1][1] if stack and stack[-1][0] is rec else -1
        depth = sum(1 for r, _ in stack if r is rec)
        frame = (rec, rec._new_id())
        stack.append(frame)
    status, error = "ok", None
    t0 = time.perf_counter()
    try:
        yield
    except BaseException as exc:
        status, error = "error", type(exc).__name__
        raise
    finally:
        dt = time.perf_counter() - t0
        metrics.observe_phase(name, dt)
        flight.note(name, dt, layer=layer, status=status, error=error,
                    trace_id=trace_id(), meta=meta or None)
        if frame is not None:
            stack = _stack()
            if frame in stack:
                stack.remove(frame)
            rec._add_span(SpanRecord(
                name=name, layer=layer,
                t_start=t0 - rec.epoch, seconds=dt,
                depth=depth, span_id=frame[1], parent_id=parent_id,
                thread=threading.current_thread().name,
                status=status, error=error, meta=dict(meta)))


def record(name: str, seconds: float, layer: Optional[str] = None, *,
           t_start: Optional[float] = None, **meta: Any) -> None:
    """Record an already-measured duration as a span — for call sites
    whose timing interleaves with retries or deferred work and cannot
    be a ``with`` block (the fused engine's phase splits).

    ``t_start`` is the span's real start as a ``time.perf_counter()``
    value (the ``t0`` the caller already holds). Without it the start
    is back-dated ``now - seconds``, which misorders spans whose work
    was deferred or retried between start and record — pass ``t_start``
    anywhere a true start exists."""
    metrics.observe_phase(name, seconds)
    flight.note(name, seconds, layer=layer, trace_id=trace_id(),
                meta=meta or None)
    rec = current()
    if rec is None:
        return
    stack = _stack()
    parent_id = stack[-1][1] if stack and stack[-1][0] is rec else -1
    depth = sum(1 for r, _ in stack if r is rec)
    rel = max(t_start - rec.epoch, 0.0) if t_start is not None else \
        max(time.perf_counter() - rec.epoch - seconds, 0.0)
    rec._add_span(SpanRecord(
        name=name, layer=layer, t_start=rel,
        seconds=seconds, depth=depth, span_id=rec._new_id(),
        parent_id=parent_id, thread=threading.current_thread().name,
        status="ok", error=None, meta=dict(meta)))


def record_into(recorder: SpanRecorder, name: str, seconds: float, *,
                t_start: Optional[float] = None,
                layer: Optional[str] = None, **meta: Any) -> None:
    """Record a span directly into ``recorder``, bypassing scope
    resolution — the batch leader thread uses this to graft its fused
    pack/dispatch/scatter spans into every co-batched member's
    request recorder (with a shared ``batch_id`` in ``meta``).

    Artifact-only: the phase histogram and flight ring are *not* fed
    here (the leader's own :func:`span`/:func:`record` call already
    counted the work once)."""
    rel = max(t_start - recorder.epoch, 0.0) if t_start is not None else \
        max(time.perf_counter() - recorder.epoch - seconds, 0.0)
    recorder._add_span(SpanRecord(
        name=name, layer=layer, t_start=rel, seconds=seconds,
        depth=0, span_id=recorder._new_id(), parent_id=-1,
        thread=threading.current_thread().name,
        status="ok", error=None, meta=dict(meta)))


def event(name: str, **fields: Any) -> None:
    """Point event (no duration) — recorded only while a recorder is
    active; use a metrics counter for always-on occurrence counts."""
    rec = current()
    if rec is not None:
        rec.add_event(name, dict(fields))
