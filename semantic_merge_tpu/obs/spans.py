"""Structured spans and events — the timing layer of
:mod:`semantic_merge_tpu.obs`.

A :class:`SpanRecorder` collects nestable, thread-safe span records
(monotonic wall-time, depth/parent links, ok/error status, free-form
meta) and point events. One recorder can be *activated* process-wide;
the module-level :func:`span` context manager then records into it from
any layer without plumbing a handle through every call signature — the
CLI ``Tracer`` activates one for ``--trace``/``--profile`` runs, and
``bench.py`` activates one around its instrumented merge.

Two always-on guarantees keep instrumentation writable in hot paths:

- :func:`span` and :func:`record` feed the phase histogram of
  :mod:`semantic_merge_tpu.obs.metrics` unconditionally (a dict update),
  so cumulative per-phase timing exists even without a recorder;
- full span records (nesting, meta, JSONL emission) are built only
  while a recorder is active, so dark runs pay two ``perf_counter``
  calls per span and nothing else.

Code that needs *expensive* timing fences (``block_until_ready`` on
device buffers) gates them on :func:`active` — detailed device phase
splits exist exactly when someone asked for them.

Artifacts: the recorder serializes to JSONL rows (``.semmerge-events.jsonl``,
written by ``Tracer.write``) and to the ``spans`` array summarized into
``.semmerge-trace.json``. Schemas are documented in ``runbook.md`` and
enforced by ``scripts/check_trace_schema.py``.
"""
from __future__ import annotations

import contextlib
import json
import pathlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import metrics

#: Default events artifact name (next to ``.semmerge-trace.json``).
EVENTS_ARTIFACT = ".semmerge-events.jsonl"

_state_lock = threading.Lock()
_active: "Optional[SpanRecorder]" = None
_tls = threading.local()


@dataclass(slots=True)
class SpanRecord:
    """One completed span. ``t_start`` is seconds since the recorder's
    epoch (monotonic clock); ``parent_id`` is ``-1`` for roots."""

    name: str
    layer: Optional[str]
    t_start: float
    seconds: float
    depth: int
    span_id: int
    parent_id: int
    thread: str
    status: str  # "ok" | "error"
    error: Optional[str]
    meta: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "layer": self.layer,
            "t_start": round(self.t_start, 6),
            "seconds": round(self.seconds, 6),
            "depth": self.depth,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "thread": self.thread,
            "status": self.status,
            "error": self.error,
            "meta": self.meta,
        }


class SpanRecorder:
    """Thread-safe sink for spans and events of one observed run."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next_id = 0
        self.epoch = time.perf_counter()
        self.spans: List[SpanRecord] = []
        self.events: List[dict] = []

    def _new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _add_span(self, rec: SpanRecord) -> None:
        with self._lock:
            self.spans.append(rec)

    def add_event(self, name: str, fields: Dict[str, Any]) -> None:
        row = {"name": name, "t_start": round(time.perf_counter() - self.epoch, 6),
               "thread": threading.current_thread().name, "fields": fields}
        with self._lock:
            self.events.append(row)

    # -- views ------------------------------------------------------------

    def span_dicts(self) -> List[dict]:
        with self._lock:
            return [s.to_dict() for s in
                    sorted(self.spans, key=lambda s: s.t_start)]

    def phase_totals(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        with self._lock:
            for s in self.spans:
                out[s.name] = out.get(s.name, 0.0) + s.seconds
        return out

    def layers(self) -> set:
        with self._lock:
            return {s.layer for s in self.spans if s.layer}

    def event_rows(self) -> List[dict]:
        """Every record as one JSONL-able row, time-ordered: spans carry
        ``type: "span"``, point events ``type: "event"``."""
        rows = [dict(s.to_dict(), type="span") for s in self.spans]
        with self._lock:
            rows += [dict(e, type="event") for e in self.events]
        rows.sort(key=lambda r: r["t_start"])
        return rows

    def write_jsonl(self, path: pathlib.Path | str) -> None:
        lines = [json.dumps(row, default=str) for row in self.event_rows()]
        pathlib.Path(path).write_text("\n".join(lines) + ("\n" if lines else ""),
                                      encoding="utf-8")


# ---------------------------------------------------------------------------
# Global activation

def current() -> Optional[SpanRecorder]:
    return _active


def active() -> bool:
    """True when a recorder is collecting — the gate for timing work
    with side effects (device sync fences, ``jax.live_arrays`` walks)."""
    return _active is not None


def activate(recorder: SpanRecorder) -> None:
    global _active
    with _state_lock:
        _active = recorder


def deactivate(recorder: Optional[SpanRecorder] = None) -> None:
    """Deactivate ``recorder`` (or whatever is active). A stale handle —
    some other recorder has since been activated — is a no-op, so
    overlapping Tracer lifetimes cannot clobber each other."""
    global _active
    with _state_lock:
        if recorder is None or _active is recorder:
            _active = None


@contextlib.contextmanager
def activated(recorder: SpanRecorder):
    activate(recorder)
    try:
        yield recorder
    finally:
        deactivate(recorder)


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


# ---------------------------------------------------------------------------
# Recording API

@contextlib.contextmanager
def span(name: str, layer: Optional[str] = None, **meta: Any):
    """Time a block. Always feeds the phase histogram; records a full
    :class:`SpanRecord` (with nesting links) when a recorder is active.
    Exceptions propagate and mark the span ``status="error"``."""
    rec = _active
    frame = None
    if rec is not None:
        stack = _stack()
        parent_id = stack[-1][1] if stack and stack[-1][0] is rec else -1
        depth = sum(1 for r, _ in stack if r is rec)
        frame = (rec, rec._new_id())
        stack.append(frame)
    status, error = "ok", None
    t0 = time.perf_counter()
    try:
        yield
    except BaseException as exc:
        status, error = "error", type(exc).__name__
        raise
    finally:
        dt = time.perf_counter() - t0
        metrics.observe_phase(name, dt)
        if frame is not None:
            stack = _stack()
            if frame in stack:
                stack.remove(frame)
            rec._add_span(SpanRecord(
                name=name, layer=layer,
                t_start=t0 - rec.epoch, seconds=dt,
                depth=depth, span_id=frame[1], parent_id=parent_id,
                thread=threading.current_thread().name,
                status=status, error=error, meta=dict(meta)))


def record(name: str, seconds: float, layer: Optional[str] = None,
           **meta: Any) -> None:
    """Record an already-measured duration as a span — for call sites
    whose timing interleaves with retries or deferred work and cannot
    be a ``with`` block (the fused engine's phase splits)."""
    metrics.observe_phase(name, seconds)
    rec = _active
    if rec is None:
        return
    stack = _stack()
    parent_id = stack[-1][1] if stack and stack[-1][0] is rec else -1
    depth = sum(1 for r, _ in stack if r is rec)
    rec._add_span(SpanRecord(
        name=name, layer=layer,
        t_start=max(time.perf_counter() - rec.epoch - seconds, 0.0),
        seconds=seconds, depth=depth, span_id=rec._new_id(),
        parent_id=parent_id, thread=threading.current_thread().name,
        status="ok", error=None, meta=dict(meta)))


def event(name: str, **fields: Any) -> None:
    """Point event (no duration) — recorded only while a recorder is
    active; use a metrics counter for always-on occurrence counts."""
    rec = _active
    if rec is not None:
        rec.add_event(name, dict(fields))
