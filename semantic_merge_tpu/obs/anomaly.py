"""Online anomaly triage — robust-z / EWMA detectors on per-phase
timings, with auto-captured triage bundles.

The sampling layer (:mod:`semantic_merge_tpu.obs.sampling`) decides
*what to keep*; this module decides *what to escalate*. Every finished
request feeds its per-phase wall seconds into one
:class:`EwmaDetector` per phase: an exponentially-weighted mean plus an
exponentially-weighted mean absolute deviation (a robust spread
estimate — one outlier cannot inflate its own threshold, because
breaching observations are excluded from the baseline update). A phase
*breaches* when its robust z-score exceeds ``SEMMERGE_ANOMALY_Z`` for
``SEMMERGE_ANOMALY_SUSTAIN`` consecutive requests; the detector then
fires exactly once and latches until the phase recovers (the same
number of consecutive in-band observations), so a sustained regression
produces one bundle, not one per request.

On fire, :class:`AnomalyTriage` captures a triage bundle through the
flight recorder (``reason="anomaly"``): the offending trace, the
nearest in-budget baseline trace (closest total latency among recent
healthy requests), and a phase-aligned diff whose top contributor is
named ``suspect_phase`` — the artifact ``semmerge trace diff`` renders
and ``scripts/check_trace_schema.py validate_triage`` pins.

Knobs: ``SEMMERGE_ANOMALY`` (``off`` disables), ``SEMMERGE_ANOMALY_Z``
(threshold, default 4.0), ``SEMMERGE_ANOMALY_MIN_N`` (warmup
observations per phase, default 32), ``SEMMERGE_ANOMALY_SUSTAIN``
(consecutive breaches to fire, default 3). Stdlib-only.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import flight, metrics

ENV_ENABLE = "SEMMERGE_ANOMALY"
ENV_Z = "SEMMERGE_ANOMALY_Z"
ENV_MIN_N = "SEMMERGE_ANOMALY_MIN_N"
ENV_SUSTAIN = "SEMMERGE_ANOMALY_SUSTAIN"

DEFAULT_Z = 4.0
DEFAULT_MIN_N = 32
DEFAULT_SUSTAIN = 3
#: EWMA smoothing for mean and deviation.
ALPHA = 0.05
#: Healthy requests retained as triage-diff baselines.
BASELINE_POOL = 16
#: Floor for the deviation estimate (seconds) so a perfectly-steady
#: phase cannot alert on scheduler jitter.
MIN_DEV_S = 0.0005
#: Phases cheaper than this never alert (noise floor).
MIN_MEAN_S = 0.0002


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def enabled() -> bool:
    return os.environ.get(ENV_ENABLE, "").strip().lower() not in (
        "off", "0", "false", "no")


class EwmaDetector:
    """One phase's breach detector. ``observe`` returns one of
    ``"warmup" | "ok" | "breach" | "fire" | "latched"`` — ``fire`` is
    emitted exactly once per sustained excursion."""

    __slots__ = ("z_threshold", "min_n", "sustain", "n", "mean", "dev",
                 "streak", "recovery", "latched")

    def __init__(self, z_threshold: float = DEFAULT_Z,
                 min_n: int = DEFAULT_MIN_N,
                 sustain: int = DEFAULT_SUSTAIN) -> None:
        self.z_threshold = float(z_threshold)
        self.min_n = int(min_n)
        self.sustain = max(1, int(sustain))
        self.n = 0
        self.mean = 0.0
        self.dev = 0.0
        self.streak = 0
        self.recovery = 0
        self.latched = False

    def _absorb(self, value: float) -> None:
        self.n += 1
        if self.n == 1:
            self.mean = value
            self.dev = abs(value) * 0.1
            return
        delta = value - self.mean
        self.mean += ALPHA * delta
        self.dev += ALPHA * (abs(delta) - self.dev)

    def zscore(self, value: float) -> float:
        return (value - self.mean) / max(self.dev, MIN_DEV_S)

    def observe(self, value: float) -> str:
        value = float(value)
        if self.n < self.min_n:
            self._absorb(value)
            return "warmup"
        breach = (self.zscore(value) > self.z_threshold
                  and value > max(self.mean, MIN_MEAN_S))
        if breach:
            # Breaching samples do not update the baseline — a
            # regression must not teach the detector that slow is
            # normal before it has even fired.
            self.recovery = 0
            self.streak += 1
            if self.latched:
                return "latched"
            if self.streak >= self.sustain:
                self.latched = True
                return "fire"
            return "breach"
        self._absorb(value)
        self.streak = 0
        if self.latched:
            self.recovery += 1
            if self.recovery >= self.sustain:
                self.latched = False
                self.recovery = 0
        return "ok"


class AnomalyTriage:
    """Per-phase detector bank + triage-bundle capture.

    ``observe`` is called once per finished request with its phase
    totals; when any phase fires, one bundle is written through
    :func:`flight.dump` carrying the offender, the nearest healthy
    baseline, and the phase-aligned diff."""

    def __init__(self, z_threshold: Optional[float] = None,
                 min_n: Optional[int] = None,
                 sustain: Optional[int] = None) -> None:
        self.enabled = enabled()
        self.z_threshold = (z_threshold if z_threshold is not None
                            else _env_float(ENV_Z, DEFAULT_Z))
        self.min_n = int(min_n if min_n is not None
                         else _env_float(ENV_MIN_N, DEFAULT_MIN_N))
        self.sustain = int(sustain if sustain is not None
                           else _env_float(ENV_SUSTAIN, DEFAULT_SUSTAIN))
        self._lock = threading.Lock()
        self._detectors: Dict[str, EwmaDetector] = {}
        self._baselines: deque = deque(maxlen=BASELINE_POOL)
        self._fired = 0
        self._last_bundle: Optional[str] = None

    def _detector(self, phase: str) -> EwmaDetector:
        det = self._detectors.get(phase)
        if det is None:
            det = self._detectors[phase] = EwmaDetector(
                self.z_threshold, self.min_n, self.sustain)
        return det

    def observe(self, trace_id: str, verb: str,
                phases: Dict[str, float], *,
                seconds: Optional[float] = None,
                spans: Optional[List[dict]] = None,
                root: Optional[str] = None) -> List[dict]:
        """Feed one finished request; returns the bundles captured (one
        per phase that fired this call, usually zero or one)."""
        if not self.enabled or not phases:
            return []
        total = float(seconds if seconds is not None
                      else sum(phases.values()))
        fired: List[dict] = []
        breached = False
        with self._lock:
            for phase, secs in sorted(phases.items()):
                det = self._detector(phase)
                z = det.zscore(float(secs)) if det.n >= det.min_n else 0.0
                verdict = det.observe(float(secs))
                if verdict in ("breach", "fire", "latched"):
                    breached = True
                if verdict == "fire":
                    fired.append({"phase": phase, "z": round(z, 3),
                                  "seconds": float(secs),
                                  "mean_s": round(det.mean, 6),
                                  "dev_s": round(det.dev, 6)})
            # A pre-fire "breach" must stay out of the baseline pool
            # too: the nearest-by-total selection would otherwise hand
            # the offender an identical polluted baseline and the
            # triage diff would read all-zero.
            anomalous = breached or any(
                d.latched for d in self._detectors.values())
            baseline = self._nearest_baseline(total) if fired else None
            if not anomalous:
                self._baselines.append({
                    "trace_id": str(trace_id), "verb": verb,
                    "seconds": total,
                    "phases": {k: float(v) for k, v in phases.items()}})
        bundles = []
        for hit in fired:
            bundle = self._capture(trace_id, verb, phases, total, hit,
                                   baseline, spans, root)
            if bundle is not None:
                bundles.append(bundle)
        return bundles

    def _nearest_baseline(self, total: float) -> Optional[dict]:
        if not self._baselines:
            return None
        return min(self._baselines,
                   key=lambda b: abs(b["seconds"] - total))

    def _capture(self, trace_id: str, verb: str,
                 phases: Dict[str, float], total: float, hit: dict,
                 baseline: Optional[dict],
                 spans: Optional[List[dict]],
                 root: Optional[str]) -> Optional[dict]:
        base_phases = baseline["phases"] if baseline else {}
        diff = phase_diff(phases, base_phases)
        triage = {
            "schema": 1,
            "phase": hit["phase"],
            "suspect_phase": diff["suspect_phase"] or hit["phase"],
            "z": hit["z"],
            "threshold_z": self.z_threshold,
            "sustain": self.sustain,
            "offender": {
                "trace_id": str(trace_id), "verb": verb,
                "seconds": round(total, 6),
                "phases_ms": {k: round(1000.0 * v, 3)
                              for k, v in sorted(phases.items())}},
            "baseline": ({
                "trace_id": baseline["trace_id"],
                "verb": baseline["verb"],
                "seconds": round(baseline["seconds"], 6),
                "phases_ms": {k: round(1000.0 * v, 3)
                              for k, v in
                              sorted(baseline["phases"].items())}}
                if baseline else None),
            "diff": diff["phases"],
            "ts": round(time.time(), 3),
        }
        extra: Dict[str, Any] = {"triage": triage}
        if spans:
            extra["offender_spans"] = spans
        path = flight.dump(trace_id, "anomaly", root=root, extra=extra)
        metrics.REGISTRY.counter(
            "anomaly_breaches_total",
            "Sustained per-phase latency breaches (one per excursion)"
        ).inc(1, phase=hit["phase"])
        with self._lock:
            self._fired += 1
            self._last_bundle = str(path) if path else None
        triage["bundle"] = str(path) if path else None
        return triage

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "z": self.z_threshold,
                "sustain": self.sustain,
                "fired": self._fired,
                "last_bundle": self._last_bundle,
                "latched": sorted(p for p, d in self._detectors.items()
                                  if d.latched),
                "phases_tracked": len(self._detectors),
                "baselines": len(self._baselines),
            }


def phase_diff(a_phases: Dict[str, float],
               b_phases: Dict[str, float]) -> Dict[str, Any]:
    """Phase-aligned diff of two per-phase wall-second maps (A =
    offender, B = baseline). Rows are sorted by descending delta so the
    first row — ``suspect_phase`` — names the regression's top
    contributor. Shared by auto-triage and ``semmerge trace diff``."""
    rows = []
    for phase in sorted(set(a_phases) | set(b_phases)):
        a_ms = 1000.0 * float(a_phases.get(phase, 0.0))
        b_ms = 1000.0 * float(b_phases.get(phase, 0.0))
        rows.append({
            "phase": phase,
            "a_ms": round(a_ms, 3),
            "b_ms": round(b_ms, 3),
            "delta_ms": round(a_ms - b_ms, 3),
            "ratio": round(a_ms / b_ms, 3) if b_ms > 0 else None,
        })
    rows.sort(key=lambda r: -r["delta_ms"])
    return {"phases": rows,
            "suspect_phase": rows[0]["phase"]
            if rows and rows[0]["delta_ms"] > 0 else None}
