"""Device telemetry — the accelerator-facing third of
:mod:`semantic_merge_tpu.obs`.

Captures, per run: the JAX backend/platform actually in use, compile
activity and compilation-cache hits (via ``jax.monitoring`` listeners),
host↔device transfer bytes/counts (recorded at this framework's own
``device_put``/fetch call sites — the fused engine and CRDT paths), and
live-device-buffer high-water marks. Everything lands in the shared
metrics registry, and :func:`snapshot` summarizes it for the
``.semmerge-trace.json`` artifact.

Never imports JAX on its own: the CLI's host path deliberately avoids
the multi-second JAX import, so :func:`snapshot` only reports device
state when some other layer has already brought JAX up
(``sys.modules`` probe). All listener installation is best-effort —
``jax.monitoring`` is not a stable API, so a shape change degrades to
"no compile counters", never to a broken merge.
"""
from __future__ import annotations

import sys
from typing import Dict, Optional

from . import metrics

_TRANSFER_BYTES = "semmerge_device_transfer_bytes_total"
_TRANSFER_COUNT = "semmerge_device_transfers_total"
_LIVE_BYTES_HWM = "semmerge_device_live_buffer_bytes_hwm"
_COMPILE_CACHE = "semmerge_jax_compile_cache_events_total"
_COMPILE_SECONDS = "semmerge_jax_compile_seconds_total"

_listeners_installed = False


def record_transfer(direction: str, nbytes: int, count: int = 1) -> None:
    """Account one host↔device transfer. ``direction`` is ``"h2d"`` or
    ``"d2h"``; call sites are this framework's own device_put/fetch
    points, so the numbers measure the merge pipeline, not unrelated
    JAX traffic."""
    metrics.REGISTRY.counter(
        _TRANSFER_BYTES, "Bytes moved between host and device by the "
        "merge pipeline").inc(float(nbytes), direction=direction)
    metrics.REGISTRY.counter(
        _TRANSFER_COUNT, "Host<->device transfer operations"
    ).inc(float(count), direction=direction)


def update_live_buffer_hwm() -> Optional[int]:
    """Refresh the live-device-buffer high-water mark from
    ``jax.live_arrays()``. Costs a full live-array walk — call from
    timed paths only when :func:`spans.active` (the Tracer/bench do).
    Returns the current live byte count, or ``None`` without JAX."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        live = int(sum(getattr(a, "nbytes", 0) or 0
                       for a in jax.live_arrays()))
    except Exception:
        return None
    metrics.REGISTRY.gauge(
        _LIVE_BYTES_HWM, "High-water mark of live device buffer bytes"
    ).max(float(live))
    return live


def ensure_jax_listeners() -> None:
    """Install ``jax.monitoring`` listeners that mirror compile-cache
    hits/misses and compile wall-time into the registry. Idempotent;
    call from code that has already imported JAX (the TPU backend's
    constructor does)."""
    global _listeners_installed
    if _listeners_installed or "jax" not in sys.modules:
        return
    _listeners_installed = True
    try:
        from jax import monitoring as _mon

        def _on_event(event: str, **kw) -> None:
            if "compilation_cache" in event:
                metrics.REGISTRY.counter(
                    _COMPILE_CACHE, "jax compilation-cache events"
                ).inc(1.0, event=event.rsplit("/", 1)[-1])

        def _on_duration(event: str, duration: float, **kw) -> None:
            if "compil" in event:
                metrics.REGISTRY.counter(
                    _COMPILE_SECONDS, "Cumulative JAX compile seconds"
                ).inc(float(duration), event=event.rsplit("/", 1)[-1])

        _mon.register_event_listener(_on_event)
        _mon.register_event_duration_secs_listener(_on_duration)
    except Exception:  # monitoring API drift — degrade to no counters
        pass


def _counter_by_label(name: str, label: str) -> Dict[str, float]:
    metric = metrics.REGISTRY.counter(name)
    out: Dict[str, float] = {}
    for key, value in metric._labelled():
        out[dict(key).get(label, "?")] = float(value)
    return out


def snapshot() -> dict:
    """One JSON-able record of device state for the trace artifact.

    Shape is stable (every key always present) so downstream parsers
    need no existence checks; fields that require JAX are ``None``/zero
    when JAX was never imported by this process."""
    out = {
        "jax_imported": False,
        "platform": None,
        "device_count": 0,
        "device_kinds": [],
        "process_index": 0,
        "process_count": 1,
        "live_buffer_bytes": None,
        "live_buffer_bytes_hwm": metrics.REGISTRY.gauge(
            _LIVE_BYTES_HWM).value(),
        "transfer_bytes": _counter_by_label(_TRANSFER_BYTES, "direction"),
        "transfer_count": _counter_by_label(_TRANSFER_COUNT, "direction"),
        "compile_cache_events": _counter_by_label(_COMPILE_CACHE, "event"),
        "compile_seconds": _counter_by_label(_COMPILE_SECONDS, "event"),
    }
    jax = sys.modules.get("jax")
    if jax is None:
        return out
    try:
        devices = jax.devices()
        out.update(
            jax_imported=True,
            platform=jax.default_backend(),
            device_count=len(devices),
            device_kinds=sorted({getattr(d, "device_kind", "?")
                                 for d in devices}),
            process_index=jax.process_index(),
            process_count=jax.process_count(),
        )
    except Exception:
        # A half-initialized runtime (failed plugin bring-up) must not
        # take the trace artifact down with it.
        out["jax_imported"] = True
    live = update_live_buffer_hwm()
    out["live_buffer_bytes"] = live
    out["live_buffer_bytes_hwm"] = metrics.REGISTRY.gauge(
        _LIVE_BYTES_HWM).value()
    return out
