"""Fleet-consistent tail-based trace sampling + byte-budgeted stores.

PRs 10/11/15 record a full span tree for every request and write every
stitched trace to disk — dev-tool behavior that becomes the outage at
production QPS. This module turns retention into a *decision*:

- every trace buffers in the existing flight-ring/recorder machinery
  until its terminal outcome;
- at that point :class:`SamplingPolicy` mints ONE keep/drop verdict —
  keep when the request was slow (rolling p99 estimate per verb),
  errored / degraded / breaker-tripped / resolver-engaged, or when the
  trace id falls in the deterministic 1-in-N head sample;
- the verdict travels in wire ``meta["sampling"]`` so router, member
  daemon, and subprocess worker agree about the same trace id — a
  downstream hop may *upgrade* drop→keep for outcomes only it can see
  (a failover, a transport fault), never downgrade;
- kept artifacts land in a :class:`TraceStore`, a byte-budgeted
  rotating directory that prunes oldest-first while protecting
  errored/degraded traces until nothing else is left to evict.

Head sampling is a hash of the trace id, not a coin flip, which is what
makes fleet consistency free: any process holding the same id computes
the same verdict with no coordination.

Knobs:

- ``SEMMERGE_TRACE_SAMPLE`` — head-sample rate ``N`` (keep ~1 in N of
  otherwise-uninteresting traces). Setting it (or the budget) enables
  sampling; unset, the policy keeps everything (``reason="always"``) —
  the pre-existing dev behavior every tier-1 test relies on. ``0``
  means *no* head sample: tails only.
- ``SEMMERGE_TRACE_BUDGET_MB`` — artifact-store byte budget (default
  256 MB once a store exists).
- ``SEMMERGE_TRACE_KEEP`` — artifact-store count cap (default 4096).
- ``SEMMERGE_TRACE_DIR`` — standalone-daemon sampled-trace directory
  (the fleet router keeps ``SEMMERGE_FLEET_TRACE_DIR``).

Import cost stays stdlib-only (the :mod:`obs` package contract).
"""
from __future__ import annotations

import hashlib
import json
import os
import pathlib
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import metrics

ENV_SAMPLE = "SEMMERGE_TRACE_SAMPLE"
ENV_BUDGET_MB = "SEMMERGE_TRACE_BUDGET_MB"
ENV_KEEP = "SEMMERGE_TRACE_KEEP"
ENV_TRACE_DIR = "SEMMERGE_TRACE_DIR"

#: ``meta`` key the minted decision travels under on the wire.
META_KEY = "sampling"

#: Keep reasons, most- to least-interesting. ``always`` is the
#: sampling-disabled passthrough; ``sampled-out`` is the drop verdict.
KEEP_REASONS = ("error", "degraded", "breaker", "resolver", "slow",
                "head", "always")
DROP_REASON = "sampled-out"

#: Reasons the store refuses to evict while anything else remains.
PROTECTED_REASONS = frozenset(("error", "degraded", "breaker",
                               "resolver"))

DEFAULT_BUDGET_MB = 256.0
DEFAULT_KEEP = 4096
#: Observations per verb before the rolling p99 can call anything slow.
MIN_SLOW_SAMPLES = 30
#: Rolling-estimate window per verb.
P99_WINDOW = 512


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: Optional[int]) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(float(raw))
    except ValueError:
        return default


def head_keep(trace_id: str, sample_n: int) -> bool:
    """Deterministic 1-in-N head sample: every process holding the same
    trace id reaches the same verdict with zero coordination. ``n <= 0``
    keeps nothing (tails only); ``n == 1`` keeps everything."""
    if sample_n <= 0:
        return False
    if sample_n == 1:
        return True
    digest = hashlib.sha256(str(trace_id).encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") % sample_n == 0


class Decision:
    """One minted keep/drop verdict. Immutable by convention — the only
    legal mutation across hops is :meth:`upgrade` (drop→keep)."""

    __slots__ = ("keep", "reason", "minted_by", "sample_n")

    def __init__(self, keep: bool, reason: str, *,
                 minted_by: str = "local",
                 sample_n: int = 0) -> None:
        self.keep = bool(keep)
        self.reason = str(reason)
        self.minted_by = str(minted_by)
        self.sample_n = int(sample_n)

    def to_meta(self) -> Dict[str, Any]:
        return {"keep": self.keep, "reason": self.reason,
                "minted_by": self.minted_by, "sample_n": self.sample_n}

    @classmethod
    def from_meta(cls, meta: Any) -> Optional["Decision"]:
        if not isinstance(meta, dict) or "keep" not in meta:
            return None
        return cls(bool(meta.get("keep")),
                   str(meta.get("reason") or DROP_REASON),
                   minted_by=str(meta.get("minted_by") or "unknown"),
                   sample_n=int(meta.get("sample_n") or 0))

    def upgrade(self, other: Optional["Decision"]) -> "Decision":
        """Merge with a later hop's local verdict: keep wins, the
        earliest minted keep's reason sticks, drop never overrides."""
        if other is None or self.keep or not other.keep:
            return self
        return other

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Decision(keep={self.keep}, reason={self.reason!r}, "
                f"minted_by={self.minted_by!r})")


class SamplingPolicy:
    """Tail-based sampling policy: terminal-outcome criteria + rolling
    per-verb p99 slowness + deterministic head sample.

    Thread-safe; one instance per daemon/router process. When neither
    ``SEMMERGE_TRACE_SAMPLE`` nor ``SEMMERGE_TRACE_BUDGET_MB`` is set
    the policy is *disabled* and every decision is ``keep/always`` —
    the historical write-everything behavior."""

    def __init__(self, sample_n: Optional[int] = None,
                 minted_by: str = "local") -> None:
        env_n = _env_int(ENV_SAMPLE, None)
        self.enabled = (sample_n is not None or env_n is not None
                        or bool(os.environ.get(ENV_BUDGET_MB, "").strip()))
        self.sample_n = sample_n if sample_n is not None else (
            env_n if env_n is not None else 0)
        self.minted_by = minted_by
        self._lock = threading.Lock()
        self._windows: Dict[str, deque] = {}
        self._decisions: Dict[str, int] = {}

    # -- rolling p99 ----------------------------------------------------
    def _p99(self, verb: str) -> Optional[float]:
        win = self._windows.get(verb)
        if win is None or len(win) < MIN_SLOW_SAMPLES:
            return None
        ordered = sorted(win)
        return ordered[min(len(ordered) - 1,
                           int(0.99 * len(ordered)))]

    def p99(self, verb: str) -> Optional[float]:
        with self._lock:
            return self._p99(verb)

    def observe(self, verb: str, seconds: float) -> None:
        with self._lock:
            win = self._windows.get(verb)
            if win is None:
                win = self._windows[verb] = deque(maxlen=P99_WINDOW)
            win.append(float(seconds))

    # -- the verdict ----------------------------------------------------
    def decide(self, trace_id: str, verb: str, seconds: float, *,
               error: bool = False, degraded: bool = False,
               breaker: bool = False, resolver: bool = False) -> Decision:
        """Mint the terminal verdict for one trace, then absorb its
        latency into the rolling estimate (so a burst of slow requests
        is judged against the regime *before* the burst)."""
        if not self.enabled:
            decision = Decision(True, "always", minted_by=self.minted_by,
                                sample_n=self.sample_n)
        elif error:
            decision = Decision(True, "error", minted_by=self.minted_by,
                                sample_n=self.sample_n)
        elif degraded:
            decision = Decision(True, "degraded",
                                minted_by=self.minted_by,
                                sample_n=self.sample_n)
        elif breaker:
            decision = Decision(True, "breaker", minted_by=self.minted_by,
                                sample_n=self.sample_n)
        elif resolver:
            decision = Decision(True, "resolver",
                                minted_by=self.minted_by,
                                sample_n=self.sample_n)
        else:
            with self._lock:
                p99 = self._p99(verb)
            if p99 is not None and seconds >= p99:
                decision = Decision(True, "slow", minted_by=self.minted_by,
                                    sample_n=self.sample_n)
            elif head_keep(trace_id, self.sample_n):
                decision = Decision(True, "head", minted_by=self.minted_by,
                                    sample_n=self.sample_n)
            else:
                decision = Decision(False, DROP_REASON,
                                    minted_by=self.minted_by,
                                    sample_n=self.sample_n)
        self.observe(verb, seconds)
        with self._lock:
            self._decisions[decision.reason] = \
                self._decisions.get(decision.reason, 0) + 1
        metrics.REGISTRY.counter(
            "trace_sampling_decisions_total",
            "Tail-sampling verdicts minted, by decision/reason").inc(
                1, decision="keep" if decision.keep else "drop",
                reason=decision.reason)
        return decision

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "sample_n": self.sample_n,
                "decisions": dict(self._decisions),
                "p99_ms": {
                    verb: round(1000.0 * p, 3)
                    for verb in self._windows
                    for p in (self._p99(verb),) if p is not None},
            }


# ---------------------------------------------------------------------------
# Bounded artifact directories.

def prune_dir(directory: pathlib.Path | str, *,
              max_count: Optional[int] = None,
              max_bytes: Optional[int] = None,
              pattern: str = "*.json",
              protect=None,
              counter: Optional[str] = None,
              **counter_labels: object) -> int:
    """Oldest-first pruning of an artifact directory down to count/byte
    caps. ``protect(path)`` may veto an eviction; protected files go
    only once every unprotected candidate is gone and the caps are
    still blown. Returns the number of files removed; never raises
    (retention must not add a failure to the path that triggered it)."""
    try:
        root = pathlib.Path(directory)
        entries: List[Tuple[float, int, pathlib.Path]] = []
        for path in root.glob(pattern):
            try:
                st = path.stat()
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, path))
        entries.sort()
        total = sum(size for _, size, _ in entries)
        count = len(entries)

        def over() -> bool:
            return ((max_count is not None and count > max_count)
                    or (max_bytes is not None and total > max_bytes))

        pruned = 0
        for pass_protected in (False, True):
            if not over():
                break
            for mtime, size, path in list(entries):
                if not over():
                    break
                if not pass_protected and protect is not None:
                    try:
                        if protect(path):
                            continue
                    except Exception:
                        continue
                try:
                    path.unlink()
                except OSError:
                    continue
                entries.remove((mtime, size, path))
                total -= size
                count -= 1
                pruned += 1
        if pruned and counter:
            metrics.REGISTRY.counter(counter).inc(pruned, **counter_labels)
        return pruned
    except Exception:
        return 0


class TraceStore:
    """Byte-budgeted rotating trace-artifact directory.

    Filenames stay ``<trace_id>.json`` (the shape every existing reader
    — ``trace analyze``, the fleet tests, OTLP re-export — globs for);
    protection is read from the artifact's embedded ``sampling`` block.
    Writes are atomic (tmp + rename) and pruning runs after each write,
    unprotected-oldest first, so the directory converges under the
    budget even across process restarts."""

    def __init__(self, directory: pathlib.Path | str,
                 budget_mb: Optional[float] = None,
                 max_count: Optional[int] = None) -> None:
        self.root = pathlib.Path(directory)
        self.budget_bytes = int(
            (budget_mb if budget_mb is not None
             else _env_float(ENV_BUDGET_MB, DEFAULT_BUDGET_MB)) * 1024 * 1024)
        self.max_count = (max_count if max_count is not None
                          else (_env_int(ENV_KEEP, DEFAULT_KEEP)
                                or DEFAULT_KEEP))
        self._lock = threading.Lock()
        # name -> protected? (None = unknown, read lazily at prune time
        # for files that predate this process).
        self._protected: Dict[str, Optional[bool]] = {}

    @classmethod
    def from_env(cls, env: str = ENV_TRACE_DIR) -> Optional["TraceStore"]:
        raw = os.environ.get(env, "").strip()
        return cls(raw) if raw else None

    @staticmethod
    def safe_name(trace_id: str) -> str:
        return "".join(ch if ch.isalnum() or ch in "._-" else "-"
                       for ch in str(trace_id))[:80] or "unknown"

    def path_for(self, trace_id: str) -> pathlib.Path:
        return self.root / f"{self.safe_name(trace_id)}.json"

    def _is_protected(self, path: pathlib.Path) -> bool:
        cached = self._protected.get(path.name)
        if cached is not None:
            return cached
        try:
            data = json.loads(path.read_text(encoding="utf-8"))
            reason = (data.get(META_KEY) or {}).get("reason")
            protected = reason in PROTECTED_REASONS
        except Exception:
            protected = False
        self._protected[path.name] = protected
        return protected

    def write(self, trace_id: str, payload: Dict[str, Any], *,
              decision: Optional[Decision] = None) -> Optional[pathlib.Path]:
        """Persist one kept trace (embedding the verdict under
        ``sampling``), then enforce the caps. Returns the artifact path
        or ``None`` on any failure — persistence is diagnostics, it
        must never fail the request it describes."""
        try:
            path = self.path_for(trace_id)
            body = dict(payload)
            if decision is not None and META_KEY not in body:
                body[META_KEY] = decision.to_meta()
            self.root.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".tmp")
            tmp.write_text(json.dumps(body, indent=2, default=str),
                           encoding="utf-8")
            os.replace(tmp, path)
            with self._lock:
                reason = (body.get(META_KEY) or {}).get("reason") \
                    if isinstance(body.get(META_KEY), dict) else None
                self._protected[path.name] = reason in PROTECTED_REASONS
                self._prune_locked()
            return path
        except Exception:
            return None

    def prune(self) -> int:
        with self._lock:
            return self._prune_locked()

    def _prune_locked(self) -> int:
        pruned = prune_dir(
            self.root, max_count=self.max_count,
            max_bytes=self.budget_bytes, protect=self._is_protected,
            counter="trace_store_pruned_total",
            store=str(self.root.name))
        if pruned:
            live = {p.name for p in self.root.glob("*.json")}
            for name in list(self._protected):
                if name not in live:
                    del self._protected[name]
        return pruned

    def total_bytes(self) -> int:
        total = 0
        for path in self.root.glob("*.json"):
            try:
                total += path.stat().st_size
            except OSError:
                continue
        return total

    def count(self) -> int:
        return sum(1 for _ in self.root.glob("*.json"))

    def stats(self) -> Dict[str, Any]:
        return {"dir": str(self.root), "count": self.count(),
                "bytes": self.total_bytes(),
                "budget_bytes": self.budget_bytes,
                "max_count": self.max_count}


# ---------------------------------------------------------------------------
# Span-derived outcome flags — shared by daemon and router so both ends
# classify "degraded / resolver-engaged" identically.

def outcome_flags(rows: List[dict]) -> Dict[str, bool]:
    """Scan completed span rows for the tail-keep outcome criteria."""
    degraded = False
    resolver = False
    breaker = False
    error = False
    for row in rows:
        name = str(row.get("name") or "")
        if row.get("status") == "error":
            error = True
        if name == "degradation" or name.startswith("degrade"):
            degraded = True
        if name.startswith("resolution.") or name.startswith("resolver"):
            resolver = True
        meta = row.get("meta")
        if isinstance(meta, dict) and meta.get("breaker") not in (
                None, "closed"):
            breaker = True
    return {"error": error, "degraded": degraded,
            "breaker": breaker, "resolver": resolver}
