#!/usr/bin/env python
"""Emit a bench workload as reference-worker ``buildAndDiff`` params.

Writes the exact JSON-RPC ``params`` payload the reference TypeScript
worker consumes (reference ``workers/ts/src/protocol.ts:16-21``:
``{base, left, right, config}`` snapshots), built from the same
synthetic generators ``bench.py`` times this repo with — so a capture
run in a Node-equipped environment measures the reference worker on
the *identical* workload behind ``BENCH_r*.json``.

Usage::

    python workers/node-capture/make_workload.py --preset rung3 -o rung3.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import bench  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=sorted(bench.PRESETS), default="rung3")
    ap.add_argument("-o", "--out", default=None)
    args = ap.parse_args()
    p = bench.PRESETS[args.preset]
    if "changed" in p:
        base, left, right = bench.synth_repo_sparse(p["files"], p["decls"],
                                                    p["changed"])
    else:
        base, left, right = bench.synth_repo(p["files"], p["decls"],
                                             divergent=p.get("conflicts", False))
    payload = {
        "base": base.to_dict(),
        "left": left.to_dict(),
        "right": right.to_dict(),
        "config": {"deterministicSeed": "bench"},
        "_preset": args.preset,
        "_n_files": p["files"],
    }
    out = args.out or f"{args.preset}.json"
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    print(f"wrote {out} ({os.path.getsize(out)/1e6:.1f} MB, "
          f"{p['files']} files)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
