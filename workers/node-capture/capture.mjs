#!/usr/bin/env node
// Time the reference TypeScript worker's `buildAndDiff` on a bench
// workload and emit the same one-line JSON row bench.py emits — the
// measured denominator of the BASELINE.json 50x north star.
//
// The worker is spawned verbatim (no instrumentation inside it) and
// spoken to over its own newline JSON-RPC protocol (reference
// workers/ts/src/index.ts:16-39), so the measurement includes exactly
// what a real `semmerge` run pays per merge: payload serialization,
// pipe transfer, ts.createProgram parse+bind, diff, lift, and the
// response round trip. Repeats reuse one worker process (warm V8/JIT),
// and the reported number is the best of N — matching bench.py's
// warm-path protocol.
//
// Usage:
//   cd <reference>/workers/ts && npm install && npm run build
//   python workers/node-capture/make_workload.py --preset rung3 -o rung3.json
//   node workers/node-capture/capture.mjs --worker <reference>/workers/ts/dist/index.js rung3.json
import { spawn } from "node:child_process";
import { readFileSync } from "node:fs";
import readline from "node:readline";
import { argv, exit, stderr, stdout } from "node:process";

function usage() {
  stderr.write(
    "usage: capture.mjs --worker <path/to/dist/index.js> [--repeats N] <workload.json>\n");
  exit(2);
}

let workerPath = null;
let repeats = 3;
let workloadPath = null;
for (let i = 2; i < argv.length; i++) {
  if (argv[i] === "--worker") workerPath = argv[++i];
  else if (argv[i] === "--repeats") repeats = parseInt(argv[++i], 10);
  else workloadPath = argv[i];
}
if (!workerPath || !workloadPath) usage();

const payload = JSON.parse(readFileSync(workloadPath, "utf-8"));
const nFiles = payload._n_files ?? payload.base.files.length;
const params = {
  base: payload.base, left: payload.left, right: payload.right,
  config: payload.config ?? {},
};

const child = spawn("node", [workerPath], {
  stdio: ["pipe", "pipe", "inherit"],
});
const rl = readline.createInterface({ input: child.stdout });
const pending = new Map();
rl.on("line", (line) => {
  if (!line) return;
  let msg;
  try { msg = JSON.parse(line); } catch { return; }  // stray worker output
  const entry = pending.get(msg.id);
  if (entry) { pending.delete(msg.id); entry.resolve(msg); }
});
function failAll(why) {
  for (const [, entry] of pending) entry.reject(new Error(why));
  pending.clear();
}
child.on("exit", (code, sig) => failAll(`worker exited (code=${code} sig=${sig})`));
child.on("error", (err) => failAll(`worker spawn failed: ${err}`));

let nextId = 1;
function call(method, p) {
  return new Promise((resolve, reject) => {
    const id = nextId++;
    pending.set(id, { resolve, reject });
    child.stdin.write(JSON.stringify({ jsonrpc: "2.0", id, method, params: p }) + "\n");
  });
}

let best = Infinity;
let opCount = 0;
for (let r = 0; r < repeats; r++) {
  const t0 = process.hrtime.bigint();
  let resp;
  try {
    resp = await call("buildAndDiff", params);
  } catch (err) {
    stderr.write(`capture failed: ${err.message}\n`);
    exit(1);
  }
  const dt = Number(process.hrtime.bigint() - t0) / 1e9;
  if (resp.error) {
    stderr.write(`worker error: ${JSON.stringify(resp.error)}\n`);
    child.kill();
    exit(1);
  }
  opCount = resp.result.opLogLeft.length + resp.result.opLogRight.length;
  if (dt < best) best = dt;
  stderr.write(`# repeat ${r}: ${(dt * 1e3).toFixed(1)} ms\n`);
}
child.stdin.end();
child.kill();

stdout.write(JSON.stringify({
  metric: `files buildAndDiff/sec (reference Node worker, ${payload._preset ?? "?"}, ${nFiles} files)`,
  value: Math.round((nFiles / best) * 100) / 100,
  unit: "files/sec",
  vs_baseline: 1.0,
  wall_ms: Math.round(best * 1e5) / 100,
  ops: opCount,
}) + "\n");
